"""Round repair (swarm/repair.py) + proof-carrying receipts (r16).

Covers the correction plane (exact pre-step assign vs bounded-staleness
compensation, flat-layout scatter across leaves, prefix scoping and
overflow bounds), the byte-bounded retained-round ring, the
conviction-to-correction path over a real socket round, and the
proof-receipt plane end to end — including the full rejection taxonomy
(forged evidence, stale/replayed epochs, transcript–frame mismatch,
proofs for unchallenged rounds), each rejected WITHOUT ledger effect.
"""

import threading
import types

import numpy as np
import pytest

from dalle_tpu.swarm import compression
from dalle_tpu.swarm.allreduce import CHUNK_ELEMS, run_allreduce
from dalle_tpu.swarm.audit import (AuditPolicy, AuditWorker,
                                   ProofVerifier, RoundAudit,
                                   audit_round, challenged_parts)
from dalle_tpu.swarm.chaos import (BYZANTINE_PHASES, ByzantineOp,
                                   ChaosDHT, FaultPlan,
                                   phase_of_prefix)
from dalle_tpu.swarm.dht import DHT
from dalle_tpu.swarm.health import (PROOF_MAX_BYTES, PeerHealthLedger,
                                    StrikeGossip, make_receipt,
                                    open_receipt, open_receipt_full)
from dalle_tpu.swarm.identity import Identity
from dalle_tpu.swarm.matchmaking import make_group
from dalle_tpu.swarm.repair import (RepairAction, RepairPlane,
                                    apply_flat_correction)
from dalle_tpu.swarm.screening import GradientScreen, ScreenPolicy

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import \
        Ed25519PrivateKey
except ImportError:
    from dalle_tpu.swarm._fallback_crypto import Ed25519PrivateKey


def _action(lo=0, served=None, honest=None, prefix="run_grads",
            epoch=0, part=0):
    served = np.asarray(served if served is not None
                        else [1.0, 2.0, 3.0], np.float32)
    honest = np.asarray(honest if honest is not None
                        else [1.0, 1.0, 1.0], np.float32)
    return RepairAction(prefix=prefix, epoch=epoch, part=part,
                        owner="ab" * 32, lo=lo, served=served,
                        honest=honest)


class TestApplyFlatCorrection:
    def test_exact_assign_when_served_bytes_in_place(self):
        arr = np.asarray([0.0, 1.0, 2.0, 3.0, 9.0], np.float32)
        a = _action(lo=1, served=[1.0, 2.0, 3.0],
                    honest=[7.0, 8.0, 9.0])
        assert apply_flat_correction([arr], a) is True
        assert arr.tolist() == [0.0, 7.0, 8.0, 9.0, 9.0]
        # idempotent: honest bytes are no longer the served bytes, so
        # the second application degrades to += (honest - served) —
        # callers drain actions exactly once; this pins the predicate
        assert apply_flat_correction([arr], a) is False

    def test_stale_compensation_adds_the_correction(self):
        # the window now holds a LATER vector: compensation adds
        arr = np.asarray([10.0, 20.0, 30.0], np.float32)
        a = _action(lo=0, served=[1.0, 2.0, 3.0],
                    honest=[2.0, 4.0, 6.0])
        assert apply_flat_correction([arr], a) is False
        assert arr.tolist() == [11.0, 22.0, 33.0]

    def test_scatter_across_leaf_boundaries(self):
        x = np.zeros((2, 2), np.float32)   # flat [0, 4)
        y = np.zeros(3, np.float32)        # flat [4, 7)
        a = _action(lo=3, served=[0.0, 0.0, 0.0],
                    honest=[5.0, 6.0, 7.0])
        assert apply_flat_correction([x, y], a) is True
        assert x.reshape(-1).tolist() == [0.0, 0.0, 0.0, 5.0]
        assert y.tolist() == [6.0, 7.0, 0.0]

    def test_alien_layout_is_dropped_not_guessed(self):
        arr = np.zeros(2, np.float32)
        a = _action(lo=0, served=[0.0, 0.0, 0.0],
                    honest=[1.0, 1.0, 1.0])  # window overruns target
        assert apply_flat_correction([arr], a) is None
        assert arr.tolist() == [0.0, 0.0]  # untouched
        # and the plane must not count it as a repair (the soak's
        # convicted => corrected oracle keys on "applied")
        plane = RepairPlane()
        plane.submit(a)
        assert plane.apply([arr]) == 0
        snap = plane.snapshot()
        assert snap["applied"] == 0 and snap["dropped_alien"] == 1


class TestRepairPlane:
    def test_submit_drain_and_counters(self):
        plane = RepairPlane(accept_prefix="run_grads")
        assert plane.submit(_action()) is True
        assert plane.submit(_action(prefix="run_state")) is False
        assert plane.pending() == 1
        snap = plane.snapshot()
        assert snap["submitted"] == 1 and snap["skipped_prefix"] == 1
        target = np.asarray([1.0, 2.0, 3.0], np.float32)
        assert plane.apply([target]) == 1
        assert target.tolist() == [1.0, 1.0, 1.0]
        snap = plane.snapshot()
        assert snap["applied"] == 1 and snap["applied_exact"] == 1
        assert snap["pending"] == 0

    def test_stale_landing_counted(self):
        plane = RepairPlane()
        plane.submit(_action(served=[1.0, 2.0, 3.0],
                             honest=[2.0, 3.0, 4.0]))
        target = np.asarray([5.0, 5.0, 5.0], np.float32)
        plane.apply([target])
        assert target.tolist() == [6.0, 6.0, 6.0]
        snap = plane.snapshot()
        assert snap["applied_stale"] == 1 and snap["applied_exact"] == 0

    def test_overflow_drops_oldest(self):
        plane = RepairPlane(max_actions=2)
        for e in range(3):
            plane.submit(_action(epoch=e))
        assert plane.pending() == 2
        actions = plane.drain()
        assert [a.epoch for a in actions] == [1, 2]
        assert plane.snapshot()["dropped_overflow"] == 1


class TestRepairRing:
    @staticmethod
    def _ra(epoch, nbytes):
        ra = RoundAudit("ring", epoch)
        ra.begun = True
        ra.evidence[0] = b"x" * nbytes
        return ra

    def test_retained_bytes_counts_all_planes(self):
        ra = RoundAudit("rb", 0)
        ra.frames = {1: {0: b"abcd"}}
        ra.evidence = {2: b"ee"}
        ra.self_frames = [b"fff"]
        ra.gathered = {0: np.zeros(4, np.float32)}
        ra.gather_frames = {0: {0: b"gg"}}
        assert ra.retained_bytes() == 4 + 2 + 3 + 16 + 2

    def test_byte_bound_evicts_oldest_first(self):
        w = AuditWorker(None, None, max_bytes=100)
        for e in range(3):
            w.submit(self._ra(e, 40))
        with w._lock:
            epochs = [r.epoch for r in w._pending]
        assert epochs == [1, 2]          # epoch 0 evicted by bytes
        assert w.ring_evictions == 1

    def test_count_bound_still_applies(self):
        w = AuditWorker(None, None, max_bytes=1 << 30)
        for e in range(AuditWorker.MAX_PENDING + 2):
            w.submit(self._ra(e, 1))
        with w._lock:
            epochs = [r.epoch for r in w._pending]
        assert len(epochs) == AuditWorker.MAX_PENDING
        assert epochs[0] == 2
        assert w.ring_evictions == 2

    def test_step_releases_bytes(self):
        w = AuditWorker(None, None, max_bytes=100)
        ra = self._ra(0, 40)
        ra.begun = False  # never begun: submit ignores
        w.submit(ra)
        with w._lock:
            assert w._pending_bytes == 0

    def test_single_over_budget_round_does_not_flush_the_ring(self):
        # one round bigger than the whole budget is admitted WITHOUT
        # evicting the backlog (flushing it could never make room;
        # dropping the new round would let a flagship-size part evade
        # auditing)
        w = AuditWorker(None, None, max_bytes=100)
        w.submit(self._ra(0, 40))
        w.submit(self._ra(1, 40))
        w.submit(self._ra(2, 500))
        with w._lock:
            epochs = [r.epoch for r in w._pending]
        assert epochs == [0, 1, 2]
        assert w.ring_evictions == 0


class TestPhaseScopedOps:
    def test_phase_of_prefix(self):
        assert phase_of_prefix("run_grads") == "grads"
        assert phase_of_prefix("run_grads_p") == "powersgd"
        assert phase_of_prefix("run_grads_q") == "powersgd"
        assert phase_of_prefix("run_state") == "state"
        assert phase_of_prefix("") == "grads"

    def test_strict_parse_rejects_unknown_phase(self):
        with pytest.raises(ValueError):
            ByzantineOp(kind="wrong_gather_part", phase="gradz")
        plan = FaultPlan.from_dict(
            {"byzantine": [{"kind": "wrong_gather_part",
                            "phase": "state"}]})
        assert plan.byzantine[0].phase == "state"
        with pytest.raises(ValueError):
            FaultPlan.from_dict(
                {"byzantine": [{"kind": "scale", "phaze": "state"}]})

    def test_owner_seam_filters_by_phase(self):
        stub = types.SimpleNamespace(peer_id="aa" * 32)
        chaos = ChaosDHT(stub, FaultPlan(byzantine=(
            ByzantineOp(kind="wrong_gather_part", factor=10.0,
                        phase="state"),)))
        v = np.zeros(4, np.float32)
        out = chaos.tamper_gather_part(0, 0, v, prefix="run_grads")
        assert out.tolist() == [0.0] * 4     # grads round: inert
        out = chaos.tamper_gather_part(0, 0, v, prefix="run_state")
        assert out.tolist() == [10.0] * 4    # state round: fires
        assert chaos.injected == {"byz_wrong_gather_part:state": 1}
        # unscoped ops keep the r14 any-phase + bare-counter behavior
        chaos2 = ChaosDHT(stub, FaultPlan(byzantine=(
            ByzantineOp(kind="wrong_gather_part", factor=1.0),)))
        chaos2.tamper_gather_part(0, 0, v, prefix="run_grads_p")
        assert chaos2.injected == {"byz_wrong_gather_part": 1}
        assert set(BYZANTINE_PHASES) == {"grads", "powersgd", "state"}


# -- live-socket rounds: conviction -> correction + proof evidence ---------

def _det_swarm(n, base=71):
    nodes = []
    for i in range(n):
        peers = [nodes[0].visible_address] if nodes else []
        ident = Identity(Ed25519PrivateKey.from_private_bytes(
            bytes([base + i]) * 32))
        nodes.append(DHT(initial_peers=peers, identity=ident,
                         rpc_timeout=2.0))
    return nodes


def _run_threads(fns, timeout=60):
    results = [None] * len(fns)
    errors = []

    def wrap(i, fn):
        try:
            results[i] = fn()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i, fn))
               for i, fn in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    if errors:
        raise errors[0]
    return results


@pytest.fixture(scope="module")
def wrong_owner_round():
    """One 5-peer socket round with a wrong_gather_part owner, audited
    at every member — the shared substrate for the repair and proof
    tests. Yields (nodes, pids, bad_i, outs, ras, ledgers, screen)."""
    nodes = _det_swarm(5)
    pids = [nd.peer_id for nd in nodes]
    bad_i = 2
    dhts = list(nodes)
    dhts[bad_i] = ChaosDHT(nodes[bad_i], FaultPlan(
        seed=3, byzantine=(ByzantineOp(kind="wrong_gather_part",
                                       factor=10.0),)))
    screen = GradientScreen(ScreenPolicy())
    policy = AuditPolicy(frac=1.0, fetch_timeout=2.0)
    ledgers = [PeerHealthLedger() for _ in range(5)]
    ras = [RoundAudit("rp", 0, policy) for _ in range(5)]
    rng = np.random.RandomState(9)
    base = rng.randint(-8, 9, size=400).astype(np.float32)
    tensors = [[base + i] for i in range(5)]

    def peer(i):
        g = make_group(dhts[i], "rp", epoch=0, weight=1.0,
                       matchmaking_time=2.0, min_group_size=5)
        assert g is not None and g.size == 5
        return run_allreduce(
            dhts[i], g, "rp", 0, tensors[i], weight=1.0,
            allreduce_timeout=8.0, sender_timeout=1.5,
            codec=compression.NONE, ledger=ledgers[i], screen=screen,
            max_peer_weight=100.0, audit=ras[i])

    try:
        outs = _run_threads([lambda i=i: peer(i) for i in range(5)])
        yield nodes, pids, bad_i, outs, ras, ledgers, screen, tensors
    finally:
        for nd in nodes:
            nd.shutdown()


class TestConvictionRepairs:
    def test_conviction_queues_the_exact_correction(
            self, wrong_owner_round):
        nodes, pids, bad_i, outs, ras, ledgers, screen, tensors = \
            wrong_owner_round
        i = 0  # any honest member
        plane = RepairPlane(accept_prefix="rp")
        ledger = PeerHealthLedger()
        rep = audit_round(nodes[i], ras[i], ledger, repair=plane)
        bad_part = next(k for k, m in enumerate(ras[i].owners)
                        if m.peer_id == pids[bad_i])
        assert [f["part"] for f in rep["failed"]] == [bad_part]
        assert rep["failed"][0]["why"] == "replayed-bytes-mismatch"
        assert rep["failed"][0].get("repaired") is True
        assert plane.pending() == 1
        # applying the correction onto the member's averaged output
        # restores the honest-only analytic average BIT-EXACTLY: the
        # served bytes are still in place, so the repair ASSIGNS the
        # replayed honest bytes over them
        out = [np.array(a, np.float32, copy=True) for a in outs[i]]
        assert plane.apply(out) == 1
        assert plane.snapshot()["applied_exact"] == 1
        honest = np.mean([t[0] for t in tensors], axis=0,
                         dtype=np.float32).astype(np.float32)
        lo = ras[i].part_lo(bad_part)
        hi = lo + ras[i].part_sizes[bad_part]
        assert out[0].reshape(-1)[lo:hi].tobytes() \
            == honest[lo:hi].tobytes()

    def test_unrepairable_conviction_classes_stay_detection_only(self):
        # a transcript that is ITSELF the lie yields no honest
        # reconstruction: audit_one returns no values, so nothing is
        # submitted — replay-fail classes keep r15 semantics. Pinned
        # at the plane level: only replayed-bytes-mismatch entries
        # carry "repaired".
        plane = RepairPlane(accept_prefix="nope")
        assert plane.pending() == 0
        assert plane.apply([np.zeros(3, np.float32)]) == 0


def _evidence_from(ras, ledgers, nodes, i, bad_i, pids):
    """Run the audit at member ``i`` and pop its proof-carrying event."""
    ledger = PeerHealthLedger()
    audit_round(nodes[i], ras[i], ledger)
    events = ledger.drain_events()
    assert len(events) == 1
    epoch, peer, reason, evidence = events[0]
    assert peer == pids[bad_i] and reason == "owner-audit-fail"
    assert evidence is not None
    return epoch, peer, reason, evidence


def _verifier(screen, **kw):
    args = dict(frac=1.0, chunk_elems=CHUNK_ELEMS,
                codec=compression.NONE, screen=screen,
                max_peer_weight=100.0)
    args.update(kw)
    return ProofVerifier("rp", **args)


class TestProofReceipts:
    def test_verified_proof_convicts_without_local_evidence(
            self, wrong_owner_round):
        nodes, pids, bad_i, outs, ras, ledgers, screen, _t = \
            wrong_owner_round
        epoch, peer, reason, evidence = _evidence_from(
            ras, ledgers, nodes, 0, bad_i, pids)
        v = _verifier(screen)
        assert v(evidence, peer, epoch) == "rp"
        assert v.verified == 1
        # fold through a third party's gossip: an outsider that never
        # joined the round convicts purely from the proof
        issuer = Identity.generate()
        receipt = make_receipt(issuer, "rp", peer, reason, epoch,
                               proof=evidence)
        outsider = PeerHealthLedger()
        gossip = StrikeGossip(
            types.SimpleNamespace(
                peer_id="cc" * 32, identity=Identity.generate(),
                get=lambda key, latest=True: {
                    "s1": types.SimpleNamespace(value=receipt)}),
            outsider, "rp", verifier=_verifier(screen))
        assert gossip.fold_once() == 1
        assert gossip.proofs_convicted == 1
        assert outsider.local_score(peer) == 0.0
        assert outsider.penalized(peer) is True
        refs = outsider.proof_convictions(peer)
        assert len(refs) == 1 and all(":rp:" in r for r in refs)
        # replayed receipt: idempotent (the _seen mark dedups), and a
        # re-wrapped copy by ANOTHER issuer dedups at the proven ref
        assert gossip.fold_once() == 0
        receipt2 = make_receipt(Identity.generate(), "rp", peer,
                                reason, epoch, proof=evidence)
        gossip.dht.get = lambda key, latest=True: {
            "s2": types.SimpleNamespace(value=receipt2)}
        gossip.fold_once()
        assert len(outsider.proof_convictions(peer)) == 1

    def test_plain_receipt_keeps_capped_influence(
            self, wrong_owner_round):
        nodes, pids, bad_i, _o, ras, ledgers, screen, _t = \
            wrong_owner_round
        peer = pids[bad_i]
        receipt = make_receipt(Identity.generate(), "rp", peer,
                               "owner-audit-fail", 0)  # no proof
        led = PeerHealthLedger()
        gossip = StrikeGossip(
            types.SimpleNamespace(
                peer_id="cc" * 32, identity=Identity.generate(),
                get=lambda key, latest=True: {
                    "s": types.SimpleNamespace(value=receipt)}),
            led, "rp", verifier=_verifier(screen))
        gossip.fold_once()
        # r13 semantics: an accusation without proof never convicts
        assert led.score(peer) <= led.max_remote_influence
        assert led.penalized(peer) is False
        assert not led.proof_convictions(peer)

    # -- the rejection taxonomy: each rejected WITHOUT ledger effect ----

    def _fold_one(self, screen, receipt, verifier=None):
        led = PeerHealthLedger()
        gossip = StrikeGossip(
            types.SimpleNamespace(
                peer_id="cc" * 32, identity=Identity.generate(),
                get=lambda key, latest=True: {
                    "s": types.SimpleNamespace(value=receipt)}),
            led, "rp", verifier=verifier or _verifier(screen))
        gossip.fold_once()
        return led, gossip

    def test_forged_evidence_rejected(self, wrong_owner_round):
        nodes, pids, bad_i, _o, ras, ledgers, screen, _t = \
            wrong_owner_round
        epoch, peer, reason, evidence = _evidence_from(
            ras, ledgers, nodes, 1, bad_i, pids)
        import msgpack
        obj = msgpack.unpackb(evidence, raw=False)
        # flip one byte inside the owner-signed transcript
        tr = bytearray(obj["transcript"])
        tr[len(tr) // 2] ^= 0x40
        obj["transcript"] = bytes(tr)
        forged = msgpack.packb(obj, use_bin_type=True)
        receipt = make_receipt(Identity.generate(), "rp", peer,
                               reason, epoch, proof=forged)
        led, gossip = self._fold_one(screen, receipt)
        assert gossip.proofs_rejected == 1
        assert led.snapshot() == {}  # no ledger effect at all

    def test_stale_replayed_epoch_rejected(self, wrong_owner_round):
        nodes, pids, bad_i, _o, ras, ledgers, screen, _t = \
            wrong_owner_round
        epoch, peer, reason, evidence = _evidence_from(
            ras, ledgers, nodes, 3, bad_i, pids)
        # old evidence re-wrapped under a far-future receipt epoch:
        # the replay attack that would re-convict forever
        receipt = make_receipt(
            Identity.generate(), "rp", peer, reason,
            epoch + ProofVerifier.EPOCH_SLACK + 5, proof=evidence)
        led, gossip = self._fold_one(screen, receipt)
        assert gossip.proofs_rejected == 1
        assert led.snapshot() == {}

    def test_transcript_frame_mismatch_rejected(self,
                                                wrong_owner_round):
        nodes, pids, bad_i, _o, ras, ledgers, screen, _t = \
            wrong_owner_round
        epoch, peer, reason, evidence = _evidence_from(
            ras, ledgers, nodes, 4, bad_i, pids)
        import msgpack
        obj = msgpack.unpackb(evidence, raw=False)
        # pair the accused owner's transcript with gather frames from
        # a DIFFERENT (honest) part: every frame is validly signed,
        # but by the wrong owner — the contradiction is fabricated
        honest_part = next(
            p for p, m in enumerate(ras[4].owners)
            if m.peer_id != pids[bad_i] and p in ras[4].gather_frames)
        frames = ras[4].gather_frames[honest_part]
        obj["frames"] = [frames[ci] for ci in sorted(frames)]
        mixed = msgpack.packb(obj, use_bin_type=True)
        receipt = make_receipt(Identity.generate(), "rp", peer,
                               reason, epoch, proof=mixed)
        led, gossip = self._fold_one(screen, receipt)
        assert gossip.proofs_rejected == 1
        assert led.snapshot() == {}

    def test_unchallenged_round_rejected(self, wrong_owner_round):
        nodes, pids, bad_i, _o, ras, ledgers, screen, _t = \
            wrong_owner_round
        epoch, peer, reason, evidence = _evidence_from(
            ras, ledgers, nodes, 0, bad_i, pids)
        # a verifier whose challenge set never named this part: the
        # owner owed nobody a transcript, so a "proof" about it is a
        # fabrication attempt by construction
        v = _verifier(screen, frac=0.0)
        assert v(evidence, peer, epoch) is None
        receipt = make_receipt(Identity.generate(), "rp", peer,
                               reason, epoch, proof=evidence)
        led, gossip = self._fold_one(screen, receipt, verifier=v)
        assert gossip.proofs_rejected == 1
        assert led.snapshot() == {}

    def test_wrong_accused_and_foreign_prefix_rejected(
            self, wrong_owner_round):
        nodes, pids, bad_i, _o, ras, ledgers, screen, _t = \
            wrong_owner_round
        epoch, peer, reason, evidence = _evidence_from(
            ras, ledgers, nodes, 1, bad_i, pids)
        v = _verifier(screen)
        honest_pid = next(p for p in pids if p != peer)
        assert v(evidence, honest_pid, epoch) is None  # not the owner
        v2 = ProofVerifier("otherrun", frac=1.0,
                           chunk_elems=CHUNK_ELEMS,
                           codec=compression.NONE, screen=screen,
                           max_peer_weight=100.0)
        assert v2(evidence, peer, epoch) is None  # foreign round

    def test_oversized_proof_never_parses(self):
        ident = Identity.generate()
        big = b"z" * (PROOF_MAX_BYTES + 1)
        raw = make_receipt(ident, "rp", "cd" * 32,
                           "owner-audit-fail", 1, proof=big)
        assert open_receipt_full(raw, "rp") is None

    def test_proof_receipt_readable_by_r13_open(self,
                                                wrong_owner_round):
        nodes, pids, bad_i, _o, ras, ledgers, screen, _t = \
            wrong_owner_round
        epoch, peer, reason, evidence = _evidence_from(
            ras, ledgers, nodes, 3, bad_i, pids)
        ident = Identity.generate()
        raw = make_receipt(ident, "rp", peer, reason, epoch,
                           proof=evidence)
        opened = open_receipt(raw, "rp")
        assert opened is not None and opened[1] == peer

    def test_proven_conviction_decays_with_the_window(self):
        led = PeerHealthLedger(ttl_epochs=3)
        assert led.proven_strike("cd" * 32, "owner-audit-fail", 0,
                                 ref="r1") is True
        assert led.penalized("cd" * 32) is True
        led.advance_epoch(10)
        assert led.penalized("cd" * 32) is False
        assert not led.proof_convictions("cd" * 32)
        # aged-out evidence is rejected on arrival too
        assert led.proven_strike("cd" * 32, "owner-audit-fail", 0,
                                 ref="r2") is False


class TestChallengeUnchanged:
    def test_challenge_is_prefix_scoped(self):
        # per-phase prefixes get independent challenge sets — the aux
        # phases' audits never collide with the gradient rounds'
        a = challenged_parts("run_grads", 5, 64, 0.3)
        b = challenged_parts("run_grads_p", 5, 64, 0.3)
        c = challenged_parts("run_state", 5, 64, 0.3)
        assert a != b or b != c


# -- r20 evidence by reference: fetch plane + rejection taxonomy -----------

class TestByReferenceEvidence:
    """The r20 by-reference proof plane: oversize evidence rides the
    receipt as a digest + mailbox descriptor, verifiers fetch the
    chunked bundle (budgeted, hash-checked, failover-capable) and
    replay it under the unchanged all-or-nothing predicate. Every
    fetch-plane failure below is a REJECTION with zero ledger effect —
    the attacker-writable descriptor can waste a bounded fetch budget,
    never a ledger entry."""

    def _plane(self, node, **kw):
        from dalle_tpu.swarm.audit import EvidencePlane
        args = dict(budget_s=6.0, retries=2, fetch_timeout=1.0,
                    chunk_bytes=4096)
        args.update(kw)
        return EvidencePlane(node, "rp", **args)

    def _fold_desc(self, screen, desc, peer, fetcher, epoch=0):
        """Fold one receipt whose proof is a by-ref descriptor."""
        receipt = make_receipt(Identity.generate(), "rp", peer,
                               "owner-audit-fail", epoch, proof=desc)
        led = PeerHealthLedger()
        gossip = StrikeGossip(
            types.SimpleNamespace(
                peer_id="cc" * 32, identity=Identity.generate(),
                get=lambda key, latest=True: {
                    "s": types.SimpleNamespace(value=receipt)}),
            led, "rp", verifier=_verifier(screen, fetcher=fetcher))
        gossip.fold_once()
        return led, gossip

    def test_descriptor_validation_is_strict(self):
        from dalle_tpu.swarm.audit import parse_evidence_ref
        good = {"digest": b"\x07" * 32, "size": 5000, "n_chunks": 2,
                "chunk": 4096, "addr": "addr1"}
        assert parse_evidence_ref(good, 1 << 20) is not None
        bad = [
            dict(good, digest=b"\x07" * 31),        # wrong digest len
            dict(good, size=0),                     # empty claim
            dict(good, size=(1 << 20) + 1),         # over fetch budget
            dict(good, chunk=512),                  # sub-floor chunk
            dict(good, n_chunks=3),                 # chunking mismatch
            dict(good, addr="a" * 300),             # oversized addr
            {},                                     # missing fields
        ]
        for b in bad:
            assert parse_evidence_ref(b, 1 << 20) is None

    def test_over_budget_conviction_end_to_end(self, wrong_owner_round,
                                               monkeypatch):
        """Issuer parks over-cap evidence by reference; an outsider
        with ZERO local evidence fetches, replays and convicts; the
        conviction re-serves the bundle for failover."""
        import dalle_tpu.swarm.health as health_mod
        from dalle_tpu.swarm.audit import evidence_servers_key
        nodes, pids, bad_i, _o, ras, ledgers, screen, _t = \
            wrong_owner_round
        epoch, peer, reason, evidence = _evidence_from(
            ras, ledgers, nodes, 4, bad_i, pids)
        # shrink the inline cap so THIS real evidence counts as
        # oversize (a >4MiB round would dwarf the test substrate)
        monkeypatch.setattr(health_mod, "PROOF_MAX_BYTES", 1000)
        assert len(evidence) > 1000
        issuer_led = PeerHealthLedger()
        issuer_led.requeue_events([(epoch, peer, reason, evidence)])
        store = self._plane(nodes[4])
        fetcher = self._plane(nodes[0])
        try:
            issuer = StrikeGossip(nodes[4], issuer_led, "rp")
            issuer.evidence_store = store
            assert issuer.publish_once() == 1
            assert issuer.proofs_by_reference == 1
            assert store.counters()["published"] == 1
            outsider = PeerHealthLedger()
            fold = StrikeGossip(nodes[0], outsider, "rp",
                                verifier=_verifier(screen,
                                                   fetcher=fetcher))
            assert fold.fold_once() >= 1
            assert fold.proofs_convicted == 1
            assert outsider.penalized(peer) is True
            c = fetcher.counters()
            assert c["ok"] == 1 and c["bytes"] == len(evidence)
            # conviction re-serves: the verifier re-published the
            # bundle and advertised itself for failover
            assert c["reserved"] == 1
            ads = nodes[0].get(evidence_servers_key("rp")) or {}
            import hashlib
            dg = hashlib.sha256(evidence).hexdigest()
            movers = [k for k in ads
                      if (k.decode() if isinstance(k, bytes)
                          else str(k)).startswith(dg + ".")]
            assert len(movers) >= 2  # issuer + re-server
        finally:
            store.stop()
            fetcher.stop()

    def test_digest_mismatch_rejected(self, wrong_owner_round):
        """Served bytes that do not hash to the descriptor digest are
        discarded before any caller sees them."""
        import time as _time
        from dalle_tpu.swarm.audit import _TCHDR, _evidence_tag
        nodes, pids, bad_i, _o, _ras, _led, screen, _t = \
            wrong_owner_round
        blob = b"not the evidence" * 200
        wrong_digest = bytes(32)  # hashes to nothing served
        step = 1024
        pieces = [blob[o:o + step] for o in range(0, len(blob), step)]
        exp = _time.time() + 60
        for ci, piece in enumerate(pieces):
            nodes[4].post(_evidence_tag(wrong_digest, ci),
                          _TCHDR.pack(ci, len(pieces)) + piece, exp)
        import msgpack
        desc = msgpack.packb(
            {"v": 2, "byref": 1, "digest": wrong_digest,
             "size": len(blob), "n_chunks": len(pieces), "chunk": step,
             "addr": nodes[4].visible_address}, use_bin_type=True)
        fetcher = self._plane(nodes[0])
        try:
            led, gossip = self._fold_desc(screen, desc, pids[bad_i],
                                          fetcher)
            assert gossip.proofs_rejected == 1
            assert led.snapshot() == {}
            assert fetcher.counters()["failed"] == 1
        finally:
            fetcher.stop()

    def test_truncated_chunk_stream_rejected(self, wrong_owner_round):
        """Chunks all arrive but sum short of the claimed size."""
        import time as _time
        from dalle_tpu.swarm.audit import _TCHDR, _evidence_tag
        nodes, pids, bad_i, _o, _ras, _led, screen, _t = \
            wrong_owner_round
        digest = b"\x11" * 32
        exp = _time.time() + 60
        for ci in range(2):
            nodes[4].post(_evidence_tag(digest, ci),
                          _TCHDR.pack(ci, 2) + b"q" * 512, exp)
        import msgpack
        desc = msgpack.packb(
            {"v": 2, "byref": 1, "digest": digest, "size": 4096,
             "n_chunks": 2, "chunk": 2048,
             "addr": nodes[4].visible_address}, use_bin_type=True)
        fetcher = self._plane(nodes[0])
        try:
            led, gossip = self._fold_desc(screen, desc, pids[bad_i],
                                          fetcher)
            assert gossip.proofs_rejected == 1
            assert led.snapshot() == {}
        finally:
            fetcher.stop()

    def test_oversize_claim_rejected_before_any_io(self,
                                                   wrong_owner_round):
        """A descriptor claiming more than the fetch byte budget dies
        at validation — no allocation, no wire traffic."""
        nodes, pids, bad_i, _o, _ras, _led, screen, _t = \
            wrong_owner_round
        import msgpack
        desc = msgpack.packb(
            {"v": 2, "byref": 1, "digest": b"\x22" * 32,
             "size": (1 << 20) + 1, "n_chunks": 257, "chunk": 4096,
             "addr": nodes[4].visible_address}, use_bin_type=True)
        fetcher = self._plane(nodes[0], max_bytes=1 << 20)
        try:
            led, gossip = self._fold_desc(screen, desc, pids[bad_i],
                                          fetcher)
            assert gossip.proofs_rejected == 1
            assert led.snapshot() == {}
            assert fetcher.counters()["attempted"] == 0
        finally:
            fetcher.stop()

    def test_unfetchable_within_budget_rejected(self,
                                                wrong_owner_round):
        """Nothing serves the digest: the fetch burns its bounded
        budget and the receipt folds to nothing."""
        nodes, pids, bad_i, _o, _ras, _led, screen, _t = \
            wrong_owner_round
        import msgpack
        desc = msgpack.packb(
            {"v": 2, "byref": 1, "digest": b"\x33" * 32, "size": 2048,
             "n_chunks": 1, "chunk": 2048,
             "addr": nodes[4].visible_address}, use_bin_type=True)
        fetcher = self._plane(nodes[0], budget_s=3.0, retries=1,
                              fetch_timeout=0.3)
        try:
            led, gossip = self._fold_desc(screen, desc, pids[bad_i],
                                          fetcher)
            assert gossip.proofs_rejected == 1
            assert led.snapshot() == {}
            c = fetcher.counters()
            assert c["attempted"] == 1
            assert c["failed"] + c["timeouts"] >= 1
        finally:
            fetcher.stop()

    def test_wrong_mailbox_reference_rejected(self, wrong_owner_round):
        """Chunks live on one peer, the descriptor names another (and
        nothing advertises the digest): no failover path exists."""
        import time as _time
        from dalle_tpu.swarm.audit import _TCHDR, _evidence_tag
        nodes, pids, bad_i, _o, _ras, _led, screen, _t = \
            wrong_owner_round
        blob = b"parked elsewhere" * 64
        import hashlib
        digest = hashlib.sha256(blob).digest()
        nodes[4].post(_evidence_tag(digest, 0),
                      _TCHDR.pack(0, 1) + blob, _time.time() + 60)
        import msgpack
        desc = msgpack.packb(
            {"v": 2, "byref": 1, "digest": digest, "size": len(blob),
             "n_chunks": 1, "chunk": 4096,
             "addr": nodes[3].visible_address},  # wrong mailbox
            use_bin_type=True)
        fetcher = self._plane(nodes[0], budget_s=3.0, retries=1,
                              fetch_timeout=0.3)
        try:
            led, gossip = self._fold_desc(screen, desc, pids[bad_i],
                                          fetcher)
            assert gossip.proofs_rejected == 1
            assert led.snapshot() == {}
        finally:
            fetcher.stop()

    def test_failover_to_advertised_server(self, wrong_owner_round):
        """A dead issuer address fails over to a peer that advertised
        the digest under the evsrv key."""
        nodes, _pids, _b, _o, _ras, _led, _screen, _t = \
            wrong_owner_round
        bundle = b"survivable evidence" * 100
        server = self._plane(nodes[4])
        fetcher = self._plane(nodes[1])
        try:
            import msgpack
            desc = msgpack.unpackb(server.publish(bundle), raw=False)
            desc["addr"] = nodes[3].visible_address  # serves nothing
            from dalle_tpu.swarm.audit import parse_evidence_ref
            ref = parse_evidence_ref(desc, 1 << 30)
            assert ref is not None
            got = fetcher.fetch(ref)
            assert got == bundle
            assert fetcher.counters()["failover"] == 1
        finally:
            server.stop()
            fetcher.stop()
