"""Pallas single-pass LayerNorm (ops/pallas/ln_kernels.py): numerics and
gradients against flax nn.LayerNorm, and ln_fusion model-level parity."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from dalle_tpu.ops.pallas.ln_kernels import layer_norm, ln_supported


def _flax_ln(x, scale, bias):
    mod = nn.LayerNorm(dtype=x.dtype, param_dtype=scale.dtype)
    return mod.apply({"params": {"scale": scale, "bias": bias}}, x)


def _operands(key, m=256, d=128, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return (jax.random.normal(ks[0], (m, d), dtype) * 2.0 + 0.3,
            jax.random.normal(ks[1], (d,), jnp.float32) * 0.2 + 1.0,
            jax.random.normal(ks[2], (d,), jnp.float32) * 0.1)


class TestKernelNumerics:
    def test_forward_matches_flax(self):
        x, g, b = _operands(jax.random.PRNGKey(0))
        out = layer_norm(x, g, b, 1e-6, 128, True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_flax_ln(x, g, b)),
                                   rtol=1e-5, atol=1e-5)

    def test_backward_matches_flax_autodiff(self):
        x, g, b = _operands(jax.random.PRNGKey(1))

        def loss(fn):
            return lambda *a: jnp.sum(jnp.sin(fn(*a)))

        g_k = jax.grad(loss(lambda *a: layer_norm(*a, 1e-6, 128, True)),
                       argnums=(0, 1, 2))(x, g, b)
        g_r = jax.grad(loss(_flax_ln), argnums=(0, 1, 2))(x, g, b)
        for a, r in zip(g_k, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-4, atol=1e-5)

    def test_uneven_tiles_and_jit(self):
        # m=384 with block_m=256 -> picked block 128 divides
        x, g, b = _operands(jax.random.PRNGKey(2), m=384, d=256)
        fn = jax.jit(lambda *a: layer_norm(*a, 1e-6, 256, True))
        np.testing.assert_allclose(np.asarray(fn(x, g, b)),
                                   np.asarray(_flax_ln(x, g, b)),
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_matches_flax_bf16(self):
        # SAME dtype contract as the model: bf16 x, f32 params — the two
        # lowerings must agree to bf16 rounding, not merely "be close"
        x, g, b = _operands(jax.random.PRNGKey(3), m=512, d=128)
        xb = x.astype(jnp.bfloat16)
        out = layer_norm(xb, g, b, 1e-6, 256, True).astype(jnp.float32)
        ref = _flax_ln(xb, g, b).astype(jnp.float32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-2, atol=1e-2)

    def test_supported_gate(self):
        assert ln_supported(5120, 1024)
        assert not ln_supported(256, 64)     # d % 128
        assert not ln_supported(64, 128)     # m small
        assert not ln_supported(250, 128)    # m % 8

    def test_block_pick_stays_8_aligned(self):
        # m = 8 * prime passes ln_supported; the picked block must still
        # be a multiple of 8 (TPU second-minor constraint), falling back
        # to 8 itself when no larger aligned divisor exists
        from dalle_tpu.ops.pallas.ln_kernels import _pick_block
        assert ln_supported(1096, 1024)          # 8 * 137
        assert _pick_block(1096, 256) == 8
        assert _pick_block(5120, 256) == 256
        assert _pick_block(384, 256) == 192
        x, g, b = _operands(jax.random.PRNGKey(4), m=1096, d=128)
        out = layer_norm(x, g, b, 1e-6, 256, True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_flax_ln(x, g, b)),
                                   rtol=1e-5, atol=1e-5)


class TestModelIntegration:
    """ln_fusion wiring: fused model == unfused model on the same params,
    identical parameter trees (checkpoints interchange)."""

    @staticmethod
    def _model(ln_fusion):
        from dalle_tpu.config import flagship_model_config
        from dalle_tpu.models.dalle import DALLE, init_params

        # dim 128 so ln_supported passes; head_chunk off for tiny vocab
        cfg = flagship_model_config(
            depth=9, dim=128, heads=2, head_dim=64, text_seq_len=16,
            image_grid=4, vocab_text=64, vocab_image=32, head_chunk=0,
            remat_skip_blocks=1, ln_fusion=ln_fusion)
        model = DALLE(cfg)
        params = init_params(model, jax.random.PRNGKey(0))
        return cfg, model, params

    def test_fused_matches_unfused_loss_and_grads(self, monkeypatch):
        from dalle_tpu.models import attention
        monkeypatch.setattr(attention, "_PALLAS_INTERPRET", True)

        cfg, model, params = self._model(False)
        _, model_f, params_f = self._model(True)
        assert (jax.tree.structure(params)
                == jax.tree.structure(params_f))
        text = jnp.zeros((2, cfg.text_seq_len), jnp.int32)
        image = jnp.ones((2, cfg.image_seq_len), jnp.int32)

        def loss(m):
            return lambda p: m.apply(p, text, image)[0]

        l_u = float(loss(model)(params))
        l_f = float(loss(model_f)(params))
        assert abs(l_u - l_f) / abs(l_u) < 1e-3, (l_u, l_f)

        # Forward parity is exact (loss diff 0.0 measured in f32); the
        # gradients use the analytic LN backward vs XLA's autodiff of the
        # fast-variance chain — algebraically equal, differently rounded,
        # and the per-layer ulps compound through 9 layers of backprop to
        # rel ~1e-3 (largest at the embeddings). Tolerance sized to that.
        g_u = jax.grad(loss(model))(params)
        g_f = jax.grad(loss(model_f))(params)
        for a, b in zip(jax.tree_util.tree_flatten(g_u)[0],
                        jax.tree_util.tree_flatten(g_f)[0]):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=5e-2, atol=1.5e-2)

    def test_fallback_path_matches_flax(self):
        # CPU default (no interpret opt-in): FusedLayerNorm's inline
        # fallback must equal nn.LayerNorm bit-for-bit on the same params
        from dalle_tpu.config import flagship_model_config
        from dalle_tpu.models.transformer import FusedLayerNorm

        cfg = flagship_model_config(dim=96)
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 96),
                              jnp.float32)
        g = jnp.ones((96,)) * 1.3
        b = jnp.ones((96,)) * 0.2
        y = FusedLayerNorm(cfg).apply(
            {"params": {"scale": g, "bias": b}}, x)
        ref = nn.LayerNorm(dtype=jnp.dtype(cfg.dtype),
                           param_dtype=jnp.float32).apply(
            {"params": {"scale": g, "bias": b}}, x)
        np.testing.assert_allclose(np.asarray(y, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=1e-6, atol=1e-6)
