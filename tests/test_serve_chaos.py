"""Serving-plane chaos + overload SLO tests.

The load-bearing invariants, mirroring `tests/test_chaos.py` for the
swarm:

- `ServeFaultPlan` parsing is STRICT (a typoed plan must not pass as an
  inert green soak) and its decisions are seed-deterministic.
- The seam is bit-transparent when disabled: an engine (and the HTTP
  front-end) with an inert ServeChaos attached produces the same codes
  as one with no seam at all.
- Priority lanes admit high before low with a bounded low-lane bypass;
  deadline shedding refuses work BEFORE decode is spent and sheds
  queued work whose deadline became unmeetable.
- Mid-decode cancellation frees the slot within one call boundary,
  never double-resolves a handle, and the recycled slot's next occupant
  still reproduces its solo reference bit-exactly.
- The front-end's timeout path CANCELS (the r8→r11 slot leak), and
  /healthz (liveness) is split from /readyz (readiness + overload
  telemetry).
- The fast overload soak (`scripts/overload_soak.py --quick` shape)
  holds all its oracles in tier-1.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import ServingConfig, tiny_model_config
from dalle_tpu.models.dalle import DALLE, init_params
from dalle_tpu.models.decode import SamplingConfig, generate_images
from dalle_tpu.serving import engine as engine_mod
from dalle_tpu.serving.chaos import (ChaosInjectedError, Flood, ServeChaos,
                                     ServeFaultPlan, ServeFaultRule,
                                     maybe_wrap_serving)
from dalle_tpu.serving.engine import DeadlineShedError, DecodeEngine
from dalle_tpu.serving.metrics import ServingMetrics
from dalle_tpu.serving.pixels import PixelPipeline
from dalle_tpu.serving.scheduler import LANES, SlotScheduler
from dalle_tpu.serving.server import ServingHTTPServer

SAM = SamplingConfig(temperature=1.0, top_k=8)
FLAT = dict(attn_types=("axial_row", "axial_col"), depth=2)


@pytest.fixture(scope="module")
def flat_setup():
    cfg = tiny_model_config(**FLAT)
    params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture()
def slowed_chunks(monkeypatch):
    """Pace every chunk dispatch by 20 ms (numerics untouched): at
    steps_per_call=1 a tiny-config request takes ~0.7 s across ~32 call
    boundaries, so mid-decode events (cancel, front-end timeout)
    deterministically land while the slot is still live — no reliance
    on this box's wobbling decode speed."""
    real = engine_mod._chunk_fn

    def slow(cfg, n_steps, visible):
        fn = real(cfg, n_steps, visible)

        def wrapped(params, state):
            time.sleep(0.02)
            return fn(params, state)

        return wrapped

    monkeypatch.setattr(engine_mod, "_chunk_fn", slow)


def _texts(cfg, n, seed=100):
    return [np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed + i), (cfg.text_seq_len,), 2,
        cfg.vocab_text)) for i in range(n)]


class TestPlanParsing:
    def test_unknown_keys_and_ops_raise(self):
        with pytest.raises(ValueError, match="unknown plan key"):
            ServeFaultPlan.from_dict({"seeed": 1})
        with pytest.raises(ValueError, match="unknown rule key"):
            ServeFaultPlan.from_dict({"rules": [{"stall": 0.1}]})
        with pytest.raises(ValueError, match="unknown serve fault op"):
            ServeFaultPlan.from_dict({"rules": [{"ops": ["send"]}]})
        with pytest.raises(ValueError, match="unknown flood key"):
            ServeFaultPlan.from_dict({"floods": [{"t": 1, "burst": 2}]})

    def test_value_validation(self):
        with pytest.raises(ValueError, match="fail must be a probability"):
            ServeFaultRule(fail=1.5)
        with pytest.raises(ValueError, match="stall_s"):
            ServeFaultRule(stall_s=(0.5,))
        with pytest.raises(ValueError, match="stall_s"):
            ServeFaultRule(stall_s=(0.5, 0.1))
        with pytest.raises(ValueError, match="half_close only fires"):
            ServeFaultRule(ops=("pixel",), half_close=0.5)
        with pytest.raises(ValueError, match="start_s <= end_s"):
            ServeFaultRule(start_s=5.0, end_s=1.0)
        with pytest.raises(ValueError, match="burst"):
            Flood(at_s=0.0, burst=0)
        with pytest.raises(ValueError, match="at_s"):
            Flood(at_s=-1.0, burst=2)
        with pytest.raises(ValueError, match="crash_at_admission"):
            ServeFaultPlan(crash_at_admission=0)
        with pytest.raises(ValueError, match="crash_at_admission"):
            ServeFaultPlan.from_dict({"crash_at_admission": -3})

    def test_roundtrip_and_enabled(self):
        plan = ServeFaultPlan.from_json(
            '{"seed": 7, "rules": [{"ops": ["pixel"], "fail": 0.5}], '
            '"floods": [{"at_s": 1.0, "burst": 4}], '
            '"crash_at_admission": 3}')
        assert plan.enabled and plan.seed == 7
        assert plan.crash_at_admission == 3
        again = ServeFaultPlan.from_json(plan.to_json())
        assert again == plan
        assert not ServeFaultPlan().enabled
        assert ServeFaultPlan(crash_at_admission=1).enabled

    def test_maybe_wrap_disabled_paths(self):
        assert maybe_wrap_serving(None) is None
        assert maybe_wrap_serving("") is None
        assert maybe_wrap_serving('{"seed": 9}') is None  # inert plan
        wrapped = maybe_wrap_serving(
            '{"rules": [{"ops": ["pixel"], "fail": 1.0}]}')
        assert isinstance(wrapped, ServeChaos)


class TestDeterminism:
    def _pixel_verdicts(self, seed, n=32):
        chaos = ServeChaos(ServeFaultPlan(
            seed=seed, rules=(ServeFaultRule(ops=("pixel",), fail=0.5),)))
        out = []
        for rid in range(n):
            try:
                chaos.on_pixel(rid)
                out.append(False)
            except ChaosInjectedError:
                out.append(True)
        return out

    def test_same_seed_same_schedule(self):
        a, b = self._pixel_verdicts(11), self._pixel_verdicts(11)
        assert a == b
        assert any(a) and not all(a)   # p=0.5 over 32 draws: both kinds

    def test_per_channel_counter_advances(self):
        chaos = ServeChaos(ServeFaultPlan(
            seed=3, rules=(ServeFaultRule(ops=("pixel",), fail=0.5),)))
        verdicts = []
        for _ in range(16):            # SAME rid: the channel index moves
            try:
                chaos.on_pixel(0)
                verdicts.append(False)
            except ChaosInjectedError:
                verdicts.append(True)
        assert any(verdicts) and not all(verdicts)

    def test_flood_fires_exactly_once(self):
        chaos = ServeChaos(ServeFaultPlan(
            floods=(Flood(at_s=0.0, burst=3), Flood(at_s=9999.0, burst=5))))
        assert chaos.flood_due() == 3
        assert chaos.flood_due() == 0
        # the ledger records what the engine actually LANDED (the
        # capacity-capped count), not the planned burst
        assert "flood" not in chaos.injected
        chaos.note_flood(2)
        assert chaos.injected["flood"] == 2


class TestBitTransparency:
    def test_engine_output_identical_with_inert_seam(self, flat_setup):
        """The acceptance pin: an engine with a constructed-but-inert
        ServeChaos attached emits EXACTLY the codes of a sealess engine
        (both equal to the generate_images reference)."""
        cfg, params = flat_setup
        text = _texts(cfg, 1)[0]
        key = jax.random.PRNGKey(77)
        ref = np.asarray(generate_images(
            params, cfg, jnp.asarray(text[None]), key, SAM, buckets=4))[0]

        def run(chaos):
            eng = DecodeEngine(params, cfg,
                               ServingConfig(n_slots=1, steps_per_call=4),
                               sampling=SAM, chaos=chaos).start()
            try:
                return eng.submit(text, key).result(timeout=300)["codes"]
            finally:
                eng.stop()

        clean = run(None)
        seamed = run(ServeChaos(ServeFaultPlan(seed=5)))
        np.testing.assert_array_equal(clean, ref)
        np.testing.assert_array_equal(seamed, ref)

    def test_http_stream_identical_with_inert_seam(self, flat_setup):
        """HTTP face of the same pin: status, headers shape and body
        agree byte-for-byte once the wall-clock timing row (different
        across ANY two runs, seam or not) is normalized."""
        cfg, params = flat_setup
        tokens = _texts(cfg, 1)[0].tolist()

        def serve_once(chaos):
            eng = DecodeEngine(params, cfg,
                               ServingConfig(n_slots=1, steps_per_call=4),
                               sampling=SAM, chaos=chaos).start()
            httpd = ServingHTTPServer(("127.0.0.1", 0), eng,
                                      request_timeout_s=300.0)
            th = threading.Thread(target=httpd.serve_forever, daemon=True)
            th.start()
            url = f"http://127.0.0.1:{httpd.server_address[1]}"
            try:
                req = urllib.request.Request(
                    url + "/generate",
                    data=json.dumps({"tokens": tokens, "seed": 3}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=300) as resp:
                    status, ctype = resp.status, resp.headers[
                        "Content-Type"]
                    raw = resp.read()
            finally:
                httpd.shutdown()
                httpd.server_close()
                eng.stop()
                th.join(timeout=10)
            body = json.loads(raw)
            for row in body["results"]:
                for k in ("latency_s", "ttft_s", "queue_wait_s"):
                    row[k] = 0.0
            return status, ctype, json.dumps(body).encode()

        assert serve_once(None) == serve_once(
            ServeChaos(ServeFaultPlan(seed=5)))


class TestLaneScheduler:
    def test_grant_lanes_priority_and_total(self):
        sched = SlotScheduler(4, bytes_per_slot=100)
        assert sched.grant_lanes([3, 3], live=0, free=4) == [3, 1]
        assert sched.grant_lanes([6, 2], live=0, free=4) == [4, 0]
        assert sched.grant_lanes([0, 3], live=2, free=2) == [0, 2]
        with pytest.raises(ValueError, match="one entry per lane"):
            sched.grant_lanes([1], live=0, free=1)

    def test_burst_cap_applies_across_lanes(self):
        sched = SlotScheduler(8, 100, admit_burst=2)
        assert sched.grant_lanes([3, 3], live=0, free=8) == [2, 0]
        assert sum(sched.grant_lanes([1, 5], live=0, free=8)) == 2

    def test_kv_budget_clamp_with_high_queue(self):
        one_mb = 2 ** 20
        sched = SlotScheduler(8, one_mb, kv_budget_mb=3)
        assert sched.max_live == 3
        # the budget is lane-blind: a saturated high lane eats the
        # whole clamp
        assert sched.grant_lanes([5, 5], live=0, free=8) == [3, 0]
        assert sched.grant_lanes([5, 5], live=3, free=5) == [0, 0]

    def test_low_lane_bounded_bypass(self):
        sched = SlotScheduler(1, 100, low_lane_bypass=3)
        # 3 starved boundaries (high takes the only slot each time)...
        for _ in range(3):
            assert sched.grant_lanes([2, 2], live=0, free=1) == [1, 0]
        # ...then the bypass reserves the slot for low, and resets
        assert sched.grant_lanes([2, 2], live=0, free=1) == [0, 1]
        assert sched.grant_lanes([2, 2], live=0, free=1) == [1, 0]

    def test_zero_grant_boundary_starves_nobody(self):
        sched = SlotScheduler(1, 100, low_lane_bypass=2)
        for _ in range(10):           # no free slot: nothing to bypass
            assert sched.grant_lanes([2, 2], live=1, free=0) == [0, 0]
        assert sched.grant_lanes([2, 2], live=0, free=1) == [1, 0]

    def test_bypass_disabled_is_strict_priority(self):
        sched = SlotScheduler(1, 100, low_lane_bypass=None)
        for _ in range(20):
            assert sched.grant_lanes([2, 2], live=0, free=1) == [1, 0]

    def test_predict_completion_boundaries(self):
        sched = SlotScheduler(4, 100)
        # empty engine: one wave exactly
        assert sched.predict_completion_s(0, 0, 2.0) == 2.0
        # a full wave ahead: two waves
        assert sched.predict_completion_s(4, 0, 2.0) == 4.0
        assert sched.predict_completion_s(0, 4, 2.0) == 4.0
        # one under the wave boundary stays in the earlier wave
        assert sched.predict_completion_s(3, 0, 2.0) == 2.0
        # kv clamp shrinks the wave size
        clamped = SlotScheduler(4, 2 ** 20, kv_budget_mb=2)
        assert clamped.predict_completion_s(2, 0, 2.0) == 4.0

    def test_lane_priority_end_to_end(self, flat_setup):
        """3 low requests queued first, 1 high submitted last: the high
        request is admitted at the FIRST boundary (shortest queue wait)
        and every request still completes."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM)
        texts = _texts(cfg, 4)
        lows = [engine.submit(texts[i], jax.random.PRNGKey(i), lane="low")
                for i in range(3)]
        high = engine.submit(texts[3], jax.random.PRNGKey(3), lane="high")
        engine.start()
        try:
            high_row = high.result(timeout=300)
            low_rows = [h.result(timeout=300) for h in lows]
        finally:
            engine.stop()
        assert high_row["lane"] == "high"
        assert high_row["queue_wait_s"] < min(
            r["queue_wait_s"] for r in low_rows)
        snap = engine.metrics.snapshot()
        assert snap["completed"] == 4


class TestDeadlineShed:
    def test_submit_shed_before_any_decode(self, flat_setup):
        """With a measured cadence that predicts a miss, submit raises
        DeadlineShedError and nothing is queued or decoded."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))
        with engine.metrics._lock:     # inject a measured cadence
            engine.metrics._service_ema_s = 10.0
        text = np.zeros(cfg.text_seq_len, np.int32)
        with pytest.raises(DeadlineShedError, match="shed"):
            engine.submit(text, deadline_s=5.0)
        # malformed deadlines are a 400-class ValueError, NOT a shed —
        # bad input must not inflate the overload telemetry
        with pytest.raises(ValueError, match="deadline_s"):
            engine.submit(text, deadline_s=0.0)
        with pytest.raises(ValueError, match="deadline_s"):
            engine.submit(text, deadline_s=-5.0)
        snap = engine.metrics.snapshot()
        assert snap["shed"] == 1 and snap["submitted"] == 0
        assert snap["lanes"]["high"]["shed"] == 1
        # boundary condition: predicted == deadline is NOT shed
        # (strictly-greater — never refuse work that can exactly win)
        h = engine.submit(text, deadline_s=10.0)
        assert h is not None
        with pytest.raises(ValueError, match="finite"):
            engine.submit(text, deadline_s=float("inf"))
        with pytest.raises(ValueError, match="lane"):
            engine.submit(text, lane="turbo")
        engine.stop(drain=False)

    def test_queued_deadline_expiry_sheds_at_boundary(self, flat_setup):
        """A request accepted optimistically (no cadence yet) whose
        deadline passes while queued is shed at the first boundary —
        before its decode burns a slot."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4))
        handle = engine.submit(np.zeros(cfg.text_seq_len, np.int32),
                               deadline_s=0.05, lane="low")
        time.sleep(0.2)                # deadline passes pre-start
        engine.start()
        with pytest.raises(RuntimeError, match="shed"):
            handle.result(timeout=30)
        engine.stop()
        snap = engine.metrics.snapshot()
        assert snap["shed"] == 1 and snap["shed_queued"] == 1
        assert snap["completed"] == 0 and snap["cancelled"] == 0

    def test_shed_maps_to_429_over_http(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))
        with engine.metrics._lock:
            engine.metrics._service_ema_s = 50.0
        httpd = ServingHTTPServer(("127.0.0.1", 0), engine,
                                  request_timeout_s=5.0)
        th = threading.Thread(target=httpd.serve_forever, daemon=True)
        th.start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            req = urllib.request.Request(
                url + "/generate",
                data=json.dumps(
                    {"tokens": [1] * cfg.text_seq_len,
                     "deadline_s": 2.0}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=30)
            assert e.value.code == 429
            assert json.loads(e.value.read())["shed"] is True
        finally:
            httpd.shutdown()
            httpd.server_close()
            engine.stop(drain=False)
            th.join(timeout=10)


class TestCancel:
    def test_cancel_queued_resolves_immediately(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))
        handle = engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        assert engine.cancel(handle.request_id) is True
        with pytest.raises(RuntimeError, match="cancelled by client"):
            handle.result(timeout=5)
        assert engine.cancel(handle.request_id) is False   # idempotent
        assert engine.cancel(99999) is False               # unknown
        snap = engine.metrics.snapshot()
        assert snap["cancelled"] == 1 and snap["cancelled_mid_decode"] == 0
        engine.stop(drain=False)

    def test_mid_decode_cancel_frees_slot_and_parity(self, flat_setup,
                                                     slowed_chunks):
        """THE acceptance pin: cancelling a live request returns its
        slot to the scheduler within one call boundary, and the next
        occupant of that recycled slot still reproduces its solo
        reference bit-exactly (cancellation leaves no residue)."""
        cfg, params = flat_setup
        texts = _texts(cfg, 2)
        key_b = jax.random.PRNGKey(1)
        ref_b = np.asarray(generate_images(
            params, cfg, jnp.asarray(texts[1][None]), key_b, SAM,
            buckets=4))[0]
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=1),
                              sampling=SAM).start()
        try:
            h_a = engine.submit(texts[0], jax.random.PRNGKey(0))
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and engine._slots[0] is None:
                time.sleep(0.002)
            assert engine._slots[0] is not None, "A never admitted"
            h_b = engine.submit(texts[1], key_b)
            assert engine.cancel(h_a.request_id) is True
            with pytest.raises(RuntimeError, match="cancelled"):
                h_a.result(timeout=30)
            got_b = h_b.result(timeout=300)
        finally:
            engine.stop()
        np.testing.assert_array_equal(got_b["codes"], ref_b)
        snap = engine.metrics.snapshot()
        assert snap["cancelled"] == 1 and snap["cancelled_mid_decode"] == 1
        assert snap["completed"] == 1

    def test_cancel_after_completion_is_noop(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM).start()
        try:
            handle = engine.submit(_texts(cfg, 1)[0], jax.random.PRNGKey(2))
            payload = handle.result(timeout=300)
        finally:
            engine.stop()
        assert engine.cancel(handle.request_id) is False
        assert handle.result(timeout=1)["codes"].shape == \
            (cfg.image_seq_len,)
        assert payload["latency_s"] >= 0
        snap = engine.metrics.snapshot()
        assert snap["completed"] == 1 and snap["cancelled"] == 0

    def test_cancel_never_double_resolves(self, flat_setup):
        """The r9 _claim/_deliver discipline on the cancel path: a
        harvest limping in after a cancel resolved the handle must not
        deliver a second payload or feed the completion ledger."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))
        handle = engine_mod.RequestHandle(0)
        engine.metrics.record_submit(0)
        pending = engine_mod._Pending(
            0, np.zeros(cfg.text_seq_len, np.int32),
            np.zeros(2, np.uint32), handle, SamplingConfig())
        assert handle._resolve({"error": "cancelled by client"})
        engine.metrics.record_cancelled(0, mid_decode=True)
        engine._finish_harvest(
            pending, jnp.zeros((cfg.image_seq_len,), jnp.int32))
        snap = engine.metrics.snapshot()
        assert snap["cancelled"] == 1 and snap["completed"] == 0
        with pytest.raises(RuntimeError, match="cancelled"):
            handle.result(timeout=1)


class TestServerTimeoutCancel:
    def test_504_reclaims_the_slot(self, flat_setup, slowed_chunks):
        """The satellite fix: the front-end's request timeout used to
        504 while the request kept decoding (a leaked slot for the full
        decode). Now the timeout cancels mid-decode and the slot is
        free for the next request."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=1),
                              sampling=SAM).start()
        httpd = ServingHTTPServer(("127.0.0.1", 0), engine,
                                  request_timeout_s=0.2)
        th = threading.Thread(target=httpd.serve_forever, daemon=True)
        th.start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            req = urllib.request.Request(
                url + "/generate",
                data=json.dumps(
                    {"tokens": _texts(cfg, 1)[0].tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=60)
            assert e.value.code == 504
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and (
                    engine._slots[0] is not None):
                time.sleep(0.01)
            assert engine._slots[0] is None, \
                "timed-out request still owns its slot"
            snap = engine.metrics.snapshot()
            assert snap["cancelled"] >= 1
            assert snap["completed"] == 0
        finally:
            httpd.shutdown()
            httpd.server_close()
            engine.stop(drain=False)
            th.join(timeout=10)


class TestBrownout:
    def test_hysteresis_and_hold(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(
            n_slots=1, queue_capacity=10, brownout_high_frac=0.5,
            brownout_low_frac=0.25, brownout_hold_s=0.05))
        engine._update_brownout(5)       # at threshold: hold starts
        assert not engine.brownout_active
        time.sleep(0.06)
        engine._update_brownout(5)       # held long enough: engages
        assert engine.brownout_active
        engine._update_brownout(3)       # between low and high: stays
        assert engine.brownout_active
        engine._update_brownout(2)       # at/below low frac: disengages
        assert not engine.brownout_active
        engine._update_brownout(10)      # dip reset the hold timer
        assert not engine.brownout_active

    def test_brownout_trims_images_over_http(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4,
                                            brownout_max_images=1),
                              sampling=SAM).start()
        httpd = ServingHTTPServer(("127.0.0.1", 0), engine,
                                  request_timeout_s=300.0)
        th = threading.Thread(target=httpd.serve_forever, daemon=True)
        th.start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            engine._brownout = True      # force: the trim is the pin
            req = urllib.request.Request(
                url + "/generate",
                data=json.dumps({"tokens": _texts(cfg, 1)[0].tolist(),
                                 "n_images": 3, "seed": 4}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                body = json.loads(resp.read())
            assert body["brownout"] is True
            assert len(body["results"]) == 1
            # the surviving image is fold_in(seed, 0): parity unchanged
            ref = np.asarray(generate_images(
                params, cfg, jnp.asarray(_texts(cfg, 1)[0][None]),
                jax.random.fold_in(jax.random.PRNGKey(4), 0), SAM,
                buckets=4))[0]
            np.testing.assert_array_equal(body["results"][0]["codes"], ref)
            snap = engine.metrics.snapshot()
            assert snap["browned"] == 1
        finally:
            httpd.shutdown()
            httpd.server_close()
            engine.stop()
            th.join(timeout=10)


class TestPixelChaos:
    def test_injected_pixel_failure_fails_request_not_worker(
            self, flat_setup):
        cfg, params = flat_setup
        chaos = ServeChaos(ServeFaultPlan(
            seed=1, rules=(ServeFaultRule(ops=("pixel",), fail=1.0),)))
        engine = DecodeEngine(
            params, cfg, ServingConfig(n_slots=1, steps_per_call=4),
            sampling=SAM, chaos=chaos,
            pixel_pipeline=PixelPipeline(
                lambda codes: {"x": 1})).start()
        try:
            texts = _texts(cfg, 2)
            h1 = engine.submit(texts[0], jax.random.PRNGKey(0))
            h2 = engine.submit(texts[1], jax.random.PRNGKey(1))
            for h in (h1, h2):
                with pytest.raises(RuntimeError, match="chaos"):
                    h.result(timeout=300)
        finally:
            engine.stop()
        # the worker survived the first injected failure to fail the
        # second request too — and the ledger counts both as failed
        snap = engine.metrics.snapshot()
        assert snap["failed"] == 2 and snap["completed"] == 0
        assert chaos.injected["pixel_fail"] == 2

    def test_pixel_stall_delays_but_completes(self, flat_setup):
        cfg, params = flat_setup
        chaos = ServeChaos(ServeFaultPlan(
            seed=1, rules=(ServeFaultRule(ops=("pixel",),
                                          stall_s=(0.05, 0.05)),)))
        engine = DecodeEngine(
            params, cfg, ServingConfig(n_slots=1, steps_per_call=4),
            sampling=SAM, chaos=chaos,
            pixel_pipeline=PixelPipeline(
                lambda codes: {"x": 1})).start()
        try:
            got = engine.submit(_texts(cfg, 1)[0],
                                jax.random.PRNGKey(0)).result(timeout=300)
        finally:
            engine.stop()
        assert got["x"] == 1
        assert chaos.injected.get("stall", 0) >= 1


class TestFloodAndAdmitCrash:
    def test_flood_consumes_capacity_not_ledger(self, flat_setup):
        cfg, params = flat_setup
        chaos = ServeChaos(ServeFaultPlan(
            floods=(Flood(at_s=0.0, burst=3),)))
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4,
                                            queue_capacity=8),
                              sampling=SAM, chaos=chaos).start()
        try:
            text = _texts(cfg, 1)[0]
            key = jax.random.PRNGKey(0)
            ref = np.asarray(generate_images(
                params, cfg, jnp.asarray(text[None]), key, SAM,
                buckets=4))[0]
            got = engine.submit(text, key).result(timeout=300)
        finally:
            engine.stop()
        np.testing.assert_array_equal(got["codes"], ref)
        snap = engine.metrics.snapshot()
        assert snap["flood_injected"] == 3
        assert snap["submitted"] == 1 and snap["completed"] == 1
        assert chaos.injected["flood"] == 3

    def test_crash_at_admission_cancels_cleanly(self, flat_setup):
        """The engine-thread-crash seam: the first admission batch
        raises inside the _admitting window; the crash-path sweep must
        resolve the handle (no orphan) and the engine must fail fast
        afterwards."""
        cfg, params = flat_setup
        chaos = ServeChaos(ServeFaultPlan(crash_at_admission=1))
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1),
                              chaos=chaos).start()
        handle = engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        with pytest.raises(RuntimeError, match="cancelled"):
            handle.result(timeout=30)
        with pytest.raises(RuntimeError):      # crashed: fail fast
            engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        assert engine.alive is False
        assert chaos.injected["admit_crash"] == 1
        snap = engine.metrics.snapshot()
        assert snap["cancelled"] == 1
        engine.stop(drain=False)


class TestReadiness:
    def test_healthz_liveness_and_readyz_telemetry(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM).start()
        httpd = ServingHTTPServer(("127.0.0.1", 0), engine,
                                  request_timeout_s=300.0)
        th = threading.Thread(target=httpd.serve_forever, daemon=True)
        th.start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"

        def get(path):
            try:
                with urllib.request.urlopen(url + path,
                                            timeout=30) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        try:
            status, health = get("/healthz")
            assert status == 200 and health == {"ok": True}
            status, ready = get("/readyz")
            assert status == 200 and ready["ready"] is True
            for key in ("draining", "queue_full", "brownout",
                        "queue_depth_by_lane", "shed", "browned",
                        "cancelled_mid_decode", "goodput_img_per_s"):
                assert key in ready, key
            assert set(ready["queue_depth_by_lane"]) == set(LANES)
            status, stats = get("/stats")
            assert status == 200
            for key in ("lanes", "shed", "browned", "goodput_img_per_s",
                        "cancelled_mid_decode", "queue_depth_by_lane"):
                assert key in stats, key
            # a stopped engine is not live and not ready
            engine.stop()
            status, health = get("/healthz")
            assert status == 503 and health["ok"] is False
            status, ready = get("/readyz")
            assert status == 503 and ready["ready"] is False
        finally:
            httpd.shutdown()
            httpd.server_close()
            engine.stop(drain=False)
            th.join(timeout=10)


class TestOverloadSoak:
    def _args(self, **kw):
        import argparse
        # load 3x (vs the CLI's 2x default): the tier-1 gate must stay
        # green when the box runs FASTER during the soak than during
        # calibration (2-4x wobble, memory/CHAOS.md) — the
        # overload-engaged oracle needs the backlog to exist even then,
        # and the 8s p99 floor already absorbs the slow direction
        base = dict(requests=8, slots=2, steps_per_call=4, load=3.0,
                    queue_capacity=10, seed=0, request_timeout_s=60.0,
                    high_deadline_s=None, high_deadline_factor=12.0,
                    low_deadline_factor=2.5, plan=None, quick=True)
        base.update(kw)
        return argparse.Namespace(**base)

    def test_fast_soak_all_oracles_hold(self):
        """Tier-1 gate for `scripts/overload_soak.py`: a seeded 2x-
        overload trace against the fault-plan-wrapped server ends with
        every oracle green (accounting, bit-exact parity, high-lane
        p99, overload engaged, zero orphans)."""
        import sys
        from pathlib import Path
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        import overload_soak
        report = overload_soak.run_soak(self._args())
        assert report["oracles"], report
        failed = [k for k, v in report["oracles"].items() if not v]
        assert report["ok"], (failed, report["outcomes"],
                              report["server_stats"])

    @pytest.mark.slow
    def test_full_soak(self, tmp_path):
        """The full-size soak as a subprocess (the committed
        OVERLOAD_SOAK.json shape); slow-marked like every bench/soak
        path (pytest.ini)."""
        import os
        import subprocess
        import sys
        from pathlib import Path
        repo = Path(__file__).resolve().parent.parent
        out = tmp_path / "OVERLOAD_SOAK.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        res = subprocess.run(
            [sys.executable, str(repo / "scripts" / "overload_soak.py"),
             "--out", str(out)],
            capture_output=True, text=True, timeout=1800, env=env,
            cwd=repo)
        assert res.returncode == 0, \
            res.stdout[-3000:] + res.stderr[-2000:]
        report = json.loads(out.read_text())
        assert report["ok"] and all(report["oracles"].values())
