"""Overlap-bench coverage (r19): the exposed-sync math as pure units,
a small-payload smoke of the two-mode bench harness, and the
slow-marked flagship run that regenerates OVERLAP_BENCH.json's regime.
"""

import pytest

from scripts.overlap_bench import (exposed_sync, find_concurrent_hop,
                                   interval_union)


class TestOverlapMath:
    def test_interval_union_merges_overlaps(self):
        assert interval_union([]) == 0.0
        assert interval_union([(0, 1), (2, 3)]) == pytest.approx(2.0)
        assert interval_union([(0, 2), (1, 3)]) == pytest.approx(3.0)
        assert interval_union([(0, 5), (1, 2)]) == pytest.approx(5.0)
        assert interval_union([(3, 3), (3, 2)]) == 0.0  # empty/backward

    def test_exposed_sync_clips_to_envelope(self):
        # round [10, 20); ticks cover [8,12) and [15,18): hidden 5, exp 5
        hidden, exposed = exposed_sync(10.0, 10.0,
                                       [(8.0, 4.0), (15.0, 3.0)])
        assert hidden == pytest.approx(5.0)
        assert exposed == pytest.approx(5.0)
        # full coverage -> zero exposed
        hidden, exposed = exposed_sync(10.0, 10.0, [(0.0, 30.0)])
        assert hidden == pytest.approx(10.0)
        assert exposed == 0.0
        # no ticks -> the whole round is exposed
        hidden, exposed = exposed_sync(10.0, 10.0, [])
        assert hidden == 0.0 and exposed == pytest.approx(10.0)

    def test_find_concurrent_hop_strict_overlap(self):
        hop = {"peer": "p0", "phase": "ar_hop_scatter", "t0": 1.0,
               "dur_s": 1.0}
        acc_miss = {"peer": "p0", "phase": "accumulate", "t0": 2.0,
                    "dur_s": 1.0}  # touching endpoints: NOT strict
        assert find_concurrent_hop([hop, acc_miss]) is None
        acc_hit = {"peer": "p0", "phase": "accumulate", "t0": 1.5,
                   "dur_s": 1.0}
        got = find_concurrent_hop([hop, acc_miss, acc_hit])
        assert got is not None
        h, a, ov = got
        assert h is hop and a is acc_hit
        assert ov == pytest.approx(0.5)
        # non-hop phases never match
        other = {"peer": "p0", "phase": "allreduce", "t0": 1.0,
                 "dur_s": 9.0}
        assert find_concurrent_hop([other, acc_hit]) is None


class TestOverlapBench:
    def test_small_payload_smoke(self, tmp_path):
        """Both modes complete on a small synthetic payload and the
        report carries the full schema — the gate itself (>=30%) is
        only meaningful at the flagship payload, so rc is not
        asserted here."""
        import json

        from scripts.overlap_bench import main
        out = tmp_path / "OVERLAP_BENCH.json"
        main(["--elems", "2000000", "--budget-s", "2",
              "--allreduce-timeout", "60", "--out", str(out)])
        rep = json.loads(out.read_text())
        for mode in ("sequential", "pipelined"):
            row = rep["modes"][mode]
            assert row["complete"] is True
            assert row["round_wall_s"] > 0
            assert row["exposed_sync_s"] >= 0
            for p in row["peers"]:
                assert p["hop_rows"] > 0
        assert rep["modes"]["pipelined"]["pipeline_hops"] is True
        assert rep["concurrency_proof"] is not None
        assert rep["concurrency_proof"]["overlap_s"] > 0

    @pytest.mark.slow
    def test_full_bench(self, tmp_path):
        """The flagship-payload gate behind the committed
        OVERLAP_BENCH.json: >=30% exposed-sync reduction AND a
        concurrent hop/accumulate span pair."""
        from scripts.overlap_bench import main
        out = tmp_path / "OVERLAP_BENCH.json"
        rc = main(["--out", str(out)])
        assert rc == 0, f"overlap bench gate failed (see {out})"
