"""Swarm substrate tests: many real peers on loopback sockets.

The strategy SURVEY.md §4 prescribes (and hivemind upstream uses): launch N
DHT nodes in one process on 127.0.0.1, form a real swarm through real
sockets, and produce fault cases by killing peers mid-protocol.
"""

import time

import pytest
from pydantic import BaseModel, StrictFloat, StrictInt, conint

from dalle_tpu.swarm import (DHT, Identity, SchemaValidator,
                             SignatureValidator, get_dht_time, strip_owner)


def make_swarm(n, validators=lambda ident: [], **kwargs):
    """n bootstrapped peers; caller must shutdown (or use fixture)."""
    nodes = []
    for _ in range(n):
        ident = Identity.generate()
        peers = [nodes[0].visible_address] if nodes else []
        nodes.append(DHT(initial_peers=peers, identity=ident,
                         record_validators=validators(ident),
                         rpc_timeout=2.0, **kwargs))
    return nodes


@pytest.fixture
def swarm5():
    nodes = make_swarm(5)
    yield nodes
    for n in nodes:
        n.shutdown()


class TestDHT:
    def test_store_get_across_peers(self, swarm5):
        exp = get_dht_time() + 60
        assert swarm5[1].store("progress", "peerA", {"samples": 17}, exp)
        got = swarm5[4].get("progress")
        assert got is not None
        assert got[b"peerA"].value == {"samples": 17}
        assert got[b"peerA"].expiration_time == pytest.approx(exp)

    def test_subkeys_merge_from_different_writers(self, swarm5):
        exp = get_dht_time() + 60
        swarm5[0].store("metrics", "a", 1, exp)
        swarm5[2].store("metrics", "b", 2, exp)
        got = swarm5[3].get("metrics")
        assert got is not None and set(got) == {b"a", b"b"}

    def test_latest_expiration_wins(self, swarm5):
        t = get_dht_time()
        swarm5[0].store("k", "s", "old", t + 30)
        swarm5[1].store("k", "s", "new", t + 60)
        got = swarm5[2].get("k")
        assert got[b"s"].value == "new"

    def test_expired_records_vanish(self, swarm5):
        swarm5[0].store("ephemeral", "s", 1, get_dht_time() + 0.5)
        assert swarm5[1].get("ephemeral") is not None
        time.sleep(0.8)
        assert swarm5[2].get("ephemeral") is None

    def test_missing_key_returns_none(self, swarm5):
        assert swarm5[0].get("no-such-key") is None

    def test_peer_death_does_not_break_lookup(self):
        nodes = make_swarm(6)
        try:
            exp = get_dht_time() + 60
            nodes[1].store("sturdy", "s", "v", exp)
            nodes[2].shutdown()  # a volunteer leaves ungracefully
            got = nodes[5].get("sturdy")
            assert got is not None and got[b"s"].value == "v"
        finally:
            for i, n in enumerate(nodes):
                if i != 2:
                    n.shutdown()

    def test_client_mode_can_read_and_write(self):
        nodes = make_swarm(3)
        client = DHT(initial_peers=[nodes[0].visible_address],
                     client_mode=True, rpc_timeout=2.0)
        try:
            assert client.port == 0
            exp = get_dht_time() + 60
            assert client.store("from-client", "c", 42, exp)
            assert nodes[2].get("from-client")[b"c"].value == 42
            # and other peers never route to the client
            for n in nodes:
                assert client.peer_id not in n.peers()
        finally:
            client.shutdown()
            for n in nodes:
                n.shutdown()


class TestSignatures:
    @staticmethod
    def _mk(ident):
        return [SignatureValidator(ident)]

    @staticmethod
    def _by_clean_subkey(got):
        return {strip_owner(k): v for k, v in (got or {}).items()}

    def test_signed_roundtrip(self):
        nodes = make_swarm(3, validators=self._mk)
        try:
            exp = get_dht_time() + 60
            nodes[0].store("signed", "me", {"loss": 1.5}, exp)
            got = self._by_clean_subkey(nodes[2].get("signed"))
            assert got[b"me"].value == {"loss": 1.5}
        finally:
            for n in nodes:
                n.shutdown()

    def test_forged_record_rejected(self):
        """A peer storing under another's owner marker gets dropped on read
        (the reference's RSA validator guarantee, utils.py:27-30)."""
        honest, reader = Identity.generate(), Identity.generate()
        nodes = []
        nodes.append(DHT(identity=honest,
                         record_validators=[SignatureValidator(honest)],
                         rpc_timeout=2.0))
        forger_ident = Identity.generate()
        forger = DHT(initial_peers=[nodes[0].visible_address],
                     identity=forger_ident, rpc_timeout=2.0)  # no validator
        nodes.append(forger)
        nodes.append(DHT(initial_peers=[nodes[0].visible_address],
                         identity=reader,
                         record_validators=[SignatureValidator(reader)],
                         rpc_timeout=2.0))
        try:
            exp = get_dht_time() + 60
            # forge: subkey claims honest's identity, signature is garbage
            marker = SignatureValidator(honest).ownership_marker
            import msgpack as _mp
            forged_val = _mp.packb("forged") + b"\x00" * 64
            forger._lib.swarm_node_store(
                forger._node, __import__("hashlib").sha256(b"sig-k").digest(),
                b"victim" + marker, len(b"victim" + marker),
                forged_val, len(forged_val), exp)
            got = self._by_clean_subkey(nodes[2].get("sig-k"))
            assert b"victim" not in got
            # while a genuinely signed record passes
            nodes[0].store("sig-k", "victim", "real", exp)
            got = self._by_clean_subkey(nodes[2].get("sig-k"))
            assert got[b"victim"].value == "real"
        finally:
            for n in nodes:
                n.shutdown()

    def test_unsigned_cannot_shadow_signed(self):
        """An unsigned record with the bare subkey must not displace a
        signed one, and protected keys reject unsigned records entirely."""
        honest = Identity.generate()
        reader_v = [SignatureValidator(Identity.generate(),
                                       protected_keys=["guarded"])]
        bootstrap = DHT(identity=honest,
                        record_validators=[SignatureValidator(
                            honest, protected_keys=["guarded"])],
                        rpc_timeout=2.0)
        attacker = DHT(initial_peers=[bootstrap.visible_address],
                       rpc_timeout=2.0)  # writes unsigned records
        reader = DHT(initial_peers=[bootstrap.visible_address],
                     record_validators=reader_v, rpc_timeout=2.0)
        try:
            t = get_dht_time()
            bootstrap.store("guarded", "victim", "signed-truth", t + 30)
            attacker.store("guarded", "victim", "poison", t + 3000)
            got = reader.get("guarded")
            values = [v.value for v in got.values()]
            assert values == ["signed-truth"]
        finally:
            for n in (bootstrap, attacker, reader):
                n.shutdown()


class LocalMetrics(BaseModel):
    """Reference utils.py:15-21 schema."""
    step: conint(ge=0, strict=True)
    samples_per_second: StrictFloat
    samples_accumulated: StrictInt
    loss: StrictFloat
    mini_steps: StrictInt


class TestSchema:
    def test_schema_rejects_malformed(self):
        schemas = {"m_metrics": LocalMetrics}

        def mk(ident):
            return [SchemaValidator(schemas)]

        nodes = make_swarm(3, validators=mk)
        try:
            exp = get_dht_time() + 60
            good = {"step": 1, "samples_per_second": 8.0,
                    "samples_accumulated": 64, "loss": 2.5, "mini_steps": 4}
            nodes[0].store("m_metrics", "p0", good, exp)
            nodes[1].store("m_metrics", "p1", {"step": "NaN-garbage"}, exp)
            got = nodes[2].get("m_metrics")
            assert b"p0" in got and b"p1" not in got
            # non-schema'd keys unaffected
            nodes[0].store("other", "x", "anything", exp)
            assert nodes[2].get("other")[b"x"].value == "anything"
        finally:
            for n in nodes:
                n.shutdown()


class TestDataPlane:
    def test_send_recv_tagged_fifo(self, swarm5):
        addr = swarm5[3].visible_address
        assert swarm5[0].send(addr, tag=7, payload=b"part-0")
        assert swarm5[1].send(addr, tag=7, payload=b"part-1")
        assert swarm5[2].send(addr, tag=9, payload=b"other-channel")
        assert swarm5[3].recv(9, timeout=2.0) == b"other-channel"
        first = swarm5[3].recv(7, timeout=2.0)
        second = swarm5[3].recv(7, timeout=2.0)
        assert {first, second} == {b"part-0", b"part-1"}

    def test_recv_timeout_returns_none(self, swarm5):
        t0 = time.monotonic()
        assert swarm5[0].recv(12345, timeout=0.3) is None
        assert 0.2 < time.monotonic() - t0 < 2.0

    def test_large_payload(self, swarm5):
        blob = bytes(range(256)) * 4096 * 4  # 4 MiB tensor part
        assert swarm5[0].send(swarm5[1].visible_address, 1, blob)
        assert swarm5[1].recv(1, timeout=5.0) == blob

    def test_send_to_dead_peer_fails_fast(self, swarm5):
        t0 = time.monotonic()
        ok = swarm5[0].send("127.0.0.1:1", tag=1, payload=b"x")
        assert not ok
        assert time.monotonic() - t0 < 3.0

    def test_mailbox_post_fetch(self, swarm5):
        addr = swarm5[0].visible_address
        assert swarm5[0].post(42, b"averaged-part", get_dht_time() + 10)
        assert swarm5[1].fetch(addr, 42) == b"averaged-part"
        assert swarm5[1].fetch(addr, 43) is None
        # repost replaces
        assert swarm5[0].post(42, b"v2", get_dht_time() + 10)
        assert swarm5[2].fetch(addr, 42) == b"v2"

    def test_mailbox_expiry(self, swarm5):
        addr = swarm5[0].visible_address
        swarm5[0].post(7, b"ephemeral", get_dht_time() + 0.3)
        assert swarm5[1].fetch(addr, 7) == b"ephemeral"
        time.sleep(0.5)
        assert swarm5[1].fetch(addr, 7) is None

    def test_client_mode_can_fetch(self):
        nodes = make_swarm(2)
        client = DHT(initial_peers=[nodes[0].visible_address],
                     client_mode=True, rpc_timeout=2.0)
        try:
            nodes[1].post(9, b"for-the-client", get_dht_time() + 10)
            assert client.fetch(nodes[1].visible_address, 9) \
                == b"for-the-client"
        finally:
            client.shutdown()
            for n in nodes:
                n.shutdown()


class TestIdentity:
    def test_persisted_identity_roundtrip(self, tmp_path):
        p = str(tmp_path / "id.pem")
        a = Identity.load_or_create(p)
        b = Identity.load_or_create(p)
        assert a.node_id == b.node_id
        assert Identity.generate().node_id != a.node_id

    def test_sign_verify(self):
        ident = Identity.generate()
        sig = ident.sign(b"msg")
        assert Identity.verify(ident.public_bytes, sig, b"msg")
        assert not Identity.verify(ident.public_bytes, sig, b"tampered")


class TestRelay:
    """Relay mode: a routable peer forwards traffic between client-mode
    peers that cannot reach each other (VERDICT r2 next #3; the
    reference's libp2p relay surface, arguments.py:89-124)."""

    def test_relayed_send_and_fetch(self):
        relay = DHT(rpc_timeout=2.0)
        a = DHT(client_mode=True, rpc_timeout=2.0,
                initial_peers=[relay.visible_address])
        b = DHT(client_mode=True, rpc_timeout=2.0,
                initial_peers=[relay.visible_address])
        try:
            assert a.attach_relay(relay.visible_address)
            assert b.attach_relay(relay.visible_address)
            assert "/" in a.visible_address  # relay-routed form

            # push: a -> (relay) -> b lands in b's normal recv queue
            assert a.send(b.visible_address, 42, b"hello-b", timeout=3.0)
            assert b.recv(42, timeout=3.0) == b"hello-b"

            # mailbox through the relay: b posts locally, a fetches
            # through b's attachment
            assert b.post(7, b"parked", expiration_time=get_dht_time() + 30)
            got = a.fetch(b.visible_address, 7, timeout=3.0)
            assert got == b"parked"
            # absent tags miss cleanly
            assert a.fetch(b.visible_address, 999, timeout=2.0) is None
        finally:
            for n in (a, b, relay):
                n.shutdown()

    def test_detached_target_misses(self):
        relay = DHT(rpc_timeout=2.0)
        a = DHT(client_mode=True, rpc_timeout=2.0)
        b = DHT(client_mode=True, rpc_timeout=2.0)
        try:
            assert a.attach_relay(relay.visible_address)
            fake = f"{relay.visible_address}/{b.peer_id}"
            assert not a.send(fake, 1, b"x", timeout=2.0)
            assert a.fetch(fake, 1, timeout=2.0) is None
        finally:
            for n in (a, b, relay):
                n.shutdown()


class TestHolePunch:
    """DHT-coordinated TCP hole punch (VERDICT r3 next #7): two
    listener-less peers establish a direct link coordinated through the
    DHT; relayed sends/fetches then bypass the relay, and fall back to
    it when the punch never happened or the link dies."""

    def _mesh(self):
        relay = DHT(rpc_timeout=2.0)
        a = DHT(client_mode=True, rpc_timeout=2.0,
                initial_peers=[relay.visible_address])
        b = DHT(client_mode=True, rpc_timeout=2.0,
                initial_peers=[relay.visible_address])
        assert a.attach_relay(relay.visible_address)
        assert b.attach_relay(relay.visible_address)
        return relay, a, b

    def test_punch_then_direct_traffic_bypasses_relay(self):
        import threading

        relay, a, b = self._mesh()
        try:
            results = {}

            def punch(me, other, key):
                results[key] = me.punch(other.visible_address, timeout=10.0)

            ta = threading.Thread(target=punch,
                                  args=(a, b, "a"))
            tb = threading.Thread(target=punch, args=(b, a, "b"))
            ta.start(), tb.start()
            ta.join(20), tb.join(20)
            assert results.get("a") and results.get("b"), results
            assert a.has_direct(b.visible_address)
            assert b.has_direct(a.visible_address)

            base = relay.relay_traffic_served
            # pushes ride the punched link...
            assert a.send(b.visible_address, 77, b"direct!", timeout=3.0)
            assert b.recv(77, timeout=3.0) == b"direct!"
            # ...and so do mailbox fetches
            assert b.post(78, b"parked", expiration_time=get_dht_time() + 30)
            assert a.fetch(b.visible_address, 78, timeout=3.0) == b"parked"
            assert a.fetch(b.visible_address, 999, timeout=2.0) is None
            assert relay.relay_traffic_served == base, \
                "direct traffic still transited the relay"
        finally:
            for n in (a, b, relay):
                n.shutdown()

    def test_without_punch_relay_carries_traffic(self):
        relay, a, b = self._mesh()
        try:
            base = relay.relay_traffic_served
            assert a.send(b.visible_address, 80, b"via-relay", timeout=3.0)
            assert b.recv(80, timeout=3.0) == b"via-relay"
            assert relay.relay_traffic_served > base
        finally:
            for n in (a, b, relay):
                n.shutdown()

    def test_one_sided_punch_times_out_and_relay_still_works(self):
        relay, a, b = self._mesh()
        try:
            # only one side punches: no rendezvous, clean failure
            assert not a.punch(b.visible_address, timeout=2.0)
            assert not a.has_direct(b.visible_address)
            assert a.send(b.visible_address, 81, b"fallback", timeout=3.0)
            assert b.recv(81, timeout=3.0) == b"fallback"
        finally:
            for n in (a, b, relay):
                n.shutdown()


class TestRelayedAddressParsing:
    def test_attach_relay_accepts_relayed_address(self):
        """The banner advertises ``host:port/<peer id>`` as the copyable
        --initial-peers entry; attach_relay must accept that form and
        attach to the relay's host:port (ADVICE r3: rpartition(':')
        raised ValueError on the suffix)."""
        relay = DHT(rpc_timeout=2.0)
        a = DHT(client_mode=True, rpc_timeout=2.0)
        b = DHT(client_mode=True, rpc_timeout=2.0,
                initial_peers=[relay.visible_address])
        try:
            relayed_form = f"{relay.visible_address}/{relay.peer_id}"
            assert a.attach_relay(relayed_form)
            assert b.attach_relay(relay.visible_address)
            # the attachment is functional, not just rc==0
            assert b.send(a.visible_address, 11, b"via-relay", timeout=3.0)
            assert a.recv(11, timeout=3.0) == b"via-relay"
        finally:
            for n in (a, b, relay):
                n.shutdown()


def _frame_server(replies):
    """Loopback fake endpoint speaking the daemon's u32-length framing.

    ``replies`` maps the i-th received frame (across all connections) to
    a reply payload, ``("reply_close", payload)`` (reply, then close the
    connection cleanly — FIN reaches the client's pooled socket), or
    ``None`` (swallow the request: the client's read times out).
    Returns (port, frames, conns, closer).
    """
    import socket
    import threading

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    frames, conns = [], []

    def recv_exact(c, n):
        buf = b""
        while len(buf) < n:
            chunk = c.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def handle(c):
        while True:
            hdr = recv_exact(c, 4)
            if hdr is None:
                return
            ln = int.from_bytes(hdr, "big")
            payload = recv_exact(c, ln)
            if payload is None:
                return
            idx = len(frames)
            frames.append(payload)
            action = replies.get(idx, None)
            if isinstance(action, tuple) and action[0] == "reply_close":
                c.sendall(len(action[1]).to_bytes(4, "big") + action[1])
                c.close()  # handler exits: FIN lands while client idles
                return
            if action is not None:
                c.sendall(len(action).to_bytes(4, "big") + action)

    def accept_loop():
        while True:
            try:
                c, _ = srv.accept()
            except OSError:
                return
            conns.append(c)
            threading.Thread(target=handle, args=(c,),
                             daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    return srv.getsockname()[1], frames, conns, srv.close


MSG_OK = bytes([10])  # kMsgOk


class TestPooledRpcSafety:
    """ADVICE r3 (swarm.cc rpc retry): a resend is only safe while the
    server cannot have acted on the request. These tests drive the
    client's rpc() against a scripted fake endpoint."""

    def test_lost_reply_is_hard_failure_no_duplicate(self):
        """Reply lost AFTER the server consumed the request: the client
        must fail the call without resending — kMsg is not idempotent
        and the all-reduce part exchange does not de-duplicate."""
        port, frames, conns, closer = _frame_server(
            {0: MSG_OK, 1: None})  # swallow the 2nd request's reply
        node = DHT(rpc_timeout=2.0)
        try:
            addr = f"127.0.0.1:{port}"
            assert node.send(addr, 1, b"first", timeout=2.0)   # pools fd
            assert not node.send(addr, 2, b"second", timeout=1.0)
            time.sleep(1.5)  # a would-be retry fires within the timeout
            assert len(frames) == 2, (
                f"server saw {len(frames)} frames: lost-reply retry "
                f"delivered a duplicate")
        finally:
            closer()
            node.shutdown()

    def test_stale_pooled_socket_reconnects(self):
        """Server closed the pooled connection while idle: the pre-write
        probe must detect the dead socket and the call must complete on
        a fresh connection (exactly one delivery of each request)."""
        port, frames, conns, closer = _frame_server(
            {0: ("reply_close", MSG_OK), 1: MSG_OK})
        node = DHT(rpc_timeout=2.0)
        try:
            addr = f"127.0.0.1:{port}"
            assert node.send(addr, 1, b"first", timeout=2.0)
            time.sleep(0.3)    # let the server's FIN land
            assert node.send(addr, 2, b"second", timeout=2.0)
            assert len(frames) == 2
            assert len(conns) == 2  # second send went over a fresh fd
        finally:
            closer()
            node.shutdown()


class TestConnectionReuse:
    def test_many_rpcs_per_connection_latency(self):
        """The data plane keeps one pooled connection per endpoint (a TCP
        connect per RPC pays an extra round trip on real links). Checked
        functionally (hundreds of sequential RPCs work, surviving the
        pool) plus a loopback latency bound that per-RPC connects made
        flaky-slow."""
        a, b = make_swarm(2)
        try:
            payload = b"x" * 1024
            # warm the pool + queues
            for i in range(5):
                assert a.send(b.visible_address, 5, payload, timeout=2.0)
            t0 = time.monotonic()
            n = 300
            for i in range(n):
                assert a.send(b.visible_address, 5, payload, timeout=2.0)
            dt = time.monotonic() - t0
            for _ in range(n + 5):
                assert b.recv(5, timeout=2.0) is not None
            # loopback pooled RPC ~100us; allow a loaded-box margin
            assert dt / n < 0.005, f"{1e6 * dt / n:.0f}us per pooled RPC"
        finally:
            a.shutdown()
            b.shutdown()


def test_eight_peer_scale_run():
    """VERDICT r2 next #4: 8 real peers on loopback (full + client +
    relay-attached mix), a mid-run kill and a mid-run join, all through
    the real wire stack. The script asserts >= N-1 peers finish all
    epochs and prints the SWARM_SCALE.md timing table."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    # loaded-CI headroom: fewer epochs, longer deadline than the
    # interactive bench defaults
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               SWARM_SCALE_EPOCHS="3", SWARM_SCALE_DEADLINE="300")
    res = subprocess.run(
        [sys.executable, str(repo / "scripts" / "swarm_scale_bench.py"),
         "8"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=420)
    assert res.returncode == 0, res.stdout[-3000:] + res.stderr[-2000:]
    assert "peers reached epoch" in res.stdout


class TestRendezvous:
    """Rendezvous bootstrap (swarm/rendezvous.py): the IPFS-assisted
    bootstrap analogue (reference arguments.py:100-106) — shared-file
    first contact + DHT-key list repair."""

    def test_file_publish_and_fresh_peers(self, tmp_path):
        from dalle_tpu.swarm.rendezvous import RendezvousFile

        f = RendezvousFile(str(tmp_path / "rdv.txt"), max_age=60.0)
        assert f.fresh_peers() == []
        f.publish("peerA", "127.0.0.1:1111")
        f.publish("peerB", "127.0.0.1:2222")
        assert f.fresh_peers() == ["127.0.0.1:1111", "127.0.0.1:2222"]
        # re-publish replaces the peer's previous line
        f.publish("peerA", "127.0.0.1:3333")
        assert "127.0.0.1:1111" not in f.fresh_peers()
        # self-exclusion and pull-only (empty addr) no-op
        assert f.fresh_peers(exclude_peer_id="peerB") == ["127.0.0.1:3333"]
        f.publish("peerC", "")
        assert len(f.fresh_peers()) == 2

    def test_flock_failure_warns_once(self, tmp_path, caplog, monkeypatch):
        """A lockless filesystem (flock -> OSError) must be loud ONCE:
        the unlocked fallback can lose concurrent publishers' lines
        (ADVICE r5), and silent data-plane surprises are how rendezvous
        debugging sessions start."""
        import fcntl
        import logging

        from dalle_tpu.swarm import rendezvous

        def broken_flock(*a, **k):
            raise OSError("no lockd on this mount")

        monkeypatch.setattr(fcntl, "flock", broken_flock)
        monkeypatch.setattr(rendezvous, "_FLOCK_WARNED", False)
        f = rendezvous.RendezvousFile(str(tmp_path / "rdv.txt"))
        with caplog.at_level(logging.WARNING,
                             logger="dalle_tpu.swarm.rendezvous"):
            f.publish("peerA", "127.0.0.1:1111")
            f.publish("peerB", "127.0.0.1:2222")
        warns = [r for r in caplog.records
                 if "lock unavailable" in r.message]
        assert len(warns) == 1  # once, not per publish
        # the publishes themselves still landed
        assert len(f.fresh_peers()) == 2

    def test_stale_entries_age_out(self, tmp_path):
        from dalle_tpu.swarm.rendezvous import RendezvousFile

        f = RendezvousFile(str(tmp_path / "rdv.txt"), max_age=0.2)
        f.publish("peerA", "127.0.0.1:1111")
        assert f.fresh_peers() == ["127.0.0.1:1111"]
        time.sleep(0.3)
        assert f.fresh_peers() == []
        # a new publish compacts the stale line away
        f.publish("peerB", "127.0.0.1:2222")
        with open(f.path) as fh:
            assert "peerA" not in fh.read()

    def test_dht_advertise_and_discover(self, swarm5):
        from dalle_tpu.swarm.rendezvous import advertise, discover

        for node in swarm5:
            advertise(node, "exp")
        time.sleep(0.2)
        found = discover(swarm5[0], "exp")
        others = {n.visible_address for n in swarm5[1:]}
        assert others.issubset(set(found))
        assert swarm5[0].visible_address not in found  # self excluded

    def test_file_bootstrap_forms_swarm(self, tmp_path):
        """A joiner with NO initial peers finds the swarm through the
        rendezvous file alone (the zero-config first contact)."""
        from dalle_tpu.swarm.rendezvous import RendezvousFile

        f = RendezvousFile(str(tmp_path / "rdv.txt"))
        seed = DHT(initial_peers=[], identity=Identity.generate(),
                   rpc_timeout=2.0)
        try:
            f.publish(seed.peer_id, seed.visible_address)
            joiner = DHT(initial_peers=f.fresh_peers(),
                         identity=Identity.generate(), rpc_timeout=2.0)
            try:
                exp = get_dht_time() + 30
                assert joiner.store("k", "sub", {"v": 1}, exp)
                deadline = time.monotonic() + 5
                got = None
                while time.monotonic() < deadline and not got:
                    got = seed.get("k")
                    time.sleep(0.1)
                assert got and "v" in next(iter(got.values())).value
            finally:
                joiner.shutdown()
        finally:
            seed.shutdown()

    def test_concurrent_publishers_all_land(self, tmp_path):
        """N simultaneous publishers must not clobber each other's lines
        (the locked read-modify-write, r5 review finding)."""
        import threading

        from dalle_tpu.swarm.rendezvous import RendezvousFile

        f = RendezvousFile(str(tmp_path / "rdv.txt"))
        n = 8
        threads = [threading.Thread(
            target=lambda i=i: f.publish(f"peer{i}", f"127.0.0.1:{1000+i}"))
            for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(f.fresh_peers()) == n
