"""PowerSGD low-rank gradient compression (swarm/powersgd.py).

Hivemind carries PowerSGD as an upstream averager alternate (SURVEY.md §2
component 15); here it is a ``grad_compression="power_sgd"`` mode over the
same butterfly all-reduce. Tests: exactness at full rank, cross-peer Q
agreement without communication, error-feedback accumulation, wire-size
reduction, and a real two-peer convergence run through the collaborative
optimizer.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.swarm.powersgd import (PowerSGDCompressor,
                                      average_with_powersgd, orthogonalize)


def test_orthogonalize():
    rng = np.random.RandomState(0)
    p = orthogonalize(rng.randn(32, 4).astype(np.float32))
    np.testing.assert_allclose(p.T @ p, np.eye(4), atol=1e-4)


def test_full_rank_is_exact_mean():
    """With rank >= min(m, n), PowerSGD reproduces the exact mean, and
    both peers reconstruct identical tensors (lockstep phase emulation,
    the way the group all-reduce synchronizes real peers)."""
    rng = np.random.RandomState(0)
    g_a = [rng.randn(16, 6).astype(np.float32),
           rng.randn(8).astype(np.float32)]
    g_b = [rng.randn(16, 6).astype(np.float32),
           rng.randn(8).astype(np.float32)]
    want = [(a + b) / 2 for a, b in zip(g_a, g_b)]

    comp_a = PowerSGDCompressor(rank=6, min_ratio=10.0)
    comp_b = PowerSGDCompressor(rank=6, min_ratio=10.0)
    plans_a = comp_a.plan(g_a)
    plans_b = comp_b.plan(g_b)
    assert [p.index for p in plans_a] == [p.index for p in plans_b] == [0]

    ps_a = comp_a.phase1_ps(g_a, plans_a, epoch=0)
    ps_b = comp_b.phase1_ps(g_b, plans_b, epoch=0)
    avg_ps = [(x + y) / 2 for x, y in zip(ps_a, ps_b)]
    qs_a = comp_a.phase2_qs(plans_a, avg_ps)
    qs_b = comp_b.phase2_qs(plans_b, avg_ps)
    avg_qs = [(x + y) / 2 for x, y in zip(qs_a, qs_b)]
    out_a = comp_a.reconstruct(list(g_a), plans_a, avg_qs)
    out_b = comp_b.reconstruct(list(g_b), plans_b, avg_qs)

    np.testing.assert_allclose(out_a[0], want[0], rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(out_a[0], out_b[0])


def test_qs_agree_across_peers_without_communication():
    """Epoch-seeded Q: a peer that joins at epoch N derives the identical
    basis without communication (and different epochs get fresh bases)."""
    comp_a = PowerSGDCompressor(rank=4, seed=0)
    comp_b = PowerSGDCompressor(rank=4, seed=0)
    leaves = [np.zeros((32, 16), np.float32)]
    plan_a = comp_a.plan(leaves)
    plan_b = comp_b.plan(leaves)
    np.testing.assert_array_equal(comp_a._q_for(plan_a[0], epoch=7),
                                  comp_b._q_for(plan_b[0], epoch=7))
    assert not np.array_equal(comp_a._q_for(plan_a[0], epoch=7),
                              comp_a._q_for(plan_a[0], epoch=8))


def test_incomplete_round_falls_back_to_local_grads():
    """A factor round that cannot guarantee identical averaged bytes
    across survivors must NOT be reconstructed from: the peer keeps its
    exact local gradients and records no (wrong) error feedback."""
    from dalle_tpu.swarm.powersgd import IncompleteRound

    rng = np.random.RandomState(3)
    grad = rng.randn(32, 24).astype(np.float32)
    comp = PowerSGDCompressor(rank=2, min_ratio=10.0)

    def dying(tensors, phase):
        raise IncompleteRound(phase)

    out = average_with_powersgd(comp, [grad], dying, epoch=0)
    np.testing.assert_array_equal(out[0], grad)
    assert not comp._errors and not comp._mat_cache


def test_error_feedback_recovers_lost_mass():
    """A rank-1 compressor on a rank-2 gradient loses mass in round 1 but
    error feedback injects it in round 2: the two-round SUM approaches the
    two-round true gradient sum."""
    rng = np.random.RandomState(1)
    u1, v1 = rng.randn(32, 1), rng.randn(1, 24)
    u2, v2 = rng.randn(32, 1), rng.randn(1, 24)
    grad = (u1 @ v1 + 0.3 * u2 @ v2).astype(np.float32)

    comp = PowerSGDCompressor(rank=1, min_ratio=10.0)
    ident = lambda tensors, phase: [t.copy() for t in tensors]  # noqa: E731

    # advancing epochs rotate the (epoch-seeded) basis, as in production
    # where the optimizer passes its local_epoch
    out1 = average_with_powersgd(comp, [grad], ident, epoch=0)[0]
    err1 = float(np.linalg.norm(grad - out1))
    assert err1 > 0.1  # rank-1 cannot be exact on a rank-2 matrix

    # Error feedback is an asymptotic guarantee: individual rounds
    # oscillate (mass accumulates in e then dumps as the basis rotates),
    # but the CUMULATIVE average of compressed outputs converges to the
    # true gradient — which is what matters, since the optimizer consumes
    # the running sum of updates.
    outs = [out1]
    for r in range(1, 12):
        outs.append(average_with_powersgd(comp, [grad], ident, epoch=r)[0])
    cum_err = float(np.linalg.norm(np.mean(outs, axis=0) - grad))
    assert cum_err < 0.25 * err1
    # without feedback the cumulative error stays large:
    comp_nofb = PowerSGDCompressor(rank=1, min_ratio=10.0)
    outs_nofb = []
    for r in range(12):
        comp_nofb._errors.clear()  # ablate the feedback
        outs_nofb.append(
            average_with_powersgd(comp_nofb, [grad], ident, epoch=r)[0])
    nofb_err = float(np.linalg.norm(np.mean(outs_nofb, axis=0) - grad))
    assert cum_err < 0.5 * nofb_err


def test_wire_size_reduction():
    comp = PowerSGDCompressor(rank=4)
    leaves = [np.zeros((256, 128), np.float32), np.zeros(64, np.float32)]
    plans = comp.plan(leaves)
    assert [p.index for p in plans] == [0]
    ps = comp.phase1_ps(leaves, plans, epoch=0)
    qs = comp.phase2_qs(plans, ps)
    factor_elems = sum(p.size for p in ps) + sum(q.size for q in qs)
    assert factor_elems < 0.05 * leaves[0].size
    # small tensor stays raw
    assert plans[0].index == 0 and len(plans) == 1


def test_two_peer_collab_with_powersgd():
    """Two real peers over loopback co-train with power_sgd compression:
    both end bit-in-sync at the same epoch with finite params."""
    import jax
    import jax.numpy as jnp

    from dalle_tpu.config import CollabConfig
    from dalle_tpu.swarm.dht import DHT
    from dalle_tpu.swarm.identity import Identity
    from dalle_tpu.swarm.metrics import make_validators
    from dalle_tpu.swarm.optimizer import CollaborativeOptimizer
    from dalle_tpu.training.steps import TrainState
    import optax

    def node(prefix):
        ident = Identity.generate()
        return DHT(host="127.0.0.1", port=0, identity=ident,
                   record_validators=make_validators(ident, prefix))

    a = node("psgd")
    b = node("psgd")
    assert b.bootstrap(a.visible_address)

    cfg = CollabConfig(run_id="psgd", target_batch_size=32,
                       matchmaking_time=2.0, allreduce_timeout=10.0,
                       averaging_timeout=20.0, grad_compression="power_sgd",
                       powersgd_rank=2, average_state_every=0)
    tx = optax.sgd(0.1)

    from dalle_tpu.training.steps import make_apply_step
    opts = []
    for dht in (a, b):
        params = {"w": jnp.ones((16, 8), jnp.float32),
                  "b": jnp.zeros((8,), jnp.float32)}
        state = TrainState.create(params, tx)
        opt = CollaborativeOptimizer(
            dht, cfg, state, jax.jit(make_apply_step(tx)))
        opt.tracker.min_refresh_period = 0.05
        opts.append(opt)

    import time as _time

    def grads(scale):
        return {"w": jnp.full((16, 8), scale, jnp.float32),
                "b": jnp.full((8,), scale, jnp.float32)}

    def run(opt, scale):
        deadline = _time.monotonic() + 30
        while opt.local_epoch < 1 and _time.monotonic() < deadline:
            opt.step(grads(scale), batch_size=8)
            _time.sleep(0.05)
        return opt.local_epoch

    results = []
    t1 = threading.Thread(target=lambda: results.append(run(opts[0], 1.0)))
    t2 = threading.Thread(target=lambda: results.append(run(opts[1], 3.0)))
    t1.start(); t2.start()
    t1.join(60); t2.join(60)
    try:
        assert len(results) == 2 and all(e >= 1 for e in results), results
        assert opts[0].local_epoch == opts[1].local_epoch
        wa = np.asarray(opts[0].state.params["w"])
        wb = np.asarray(opts[1].state.params["w"])
        assert np.isfinite(wa).all() and np.isfinite(wb).all()
        # identical wire bytes -> identical params on both peers
        np.testing.assert_array_equal(wa, wb)
        # each peer accumulates several local microbatches of its constant
        # per-sample gradient (1.0 vs 3.0); the weighted average is between
        # the two and rank-2 on a rank-1 (constant) matrix is exact, so
        # w = 1 - 0.1 * avg lies in [1 - 0.3, 1 - 0.1]
        assert 0.65 <= float(wa.mean()) <= 0.95, float(wa.mean())
        assert np.ptp(wa) < 1e-3  # constant gradient -> uniform update
    finally:
        for opt in opts:
            opt.shutdown()
        a.shutdown()
        b.shutdown()


def test_device_resident_math_and_outputs():
    """VERDICT r2 weak #2 / next #2: the O(m*n*r) PowerSGD math must run
    on device — planned outputs and error-feedback buffers are jax Arrays,
    and only rank-r factors (plus unplanned tail tensors) cross the wire."""
    comp = PowerSGDCompressor(rank=4)
    leaves = [jnp.ones((256, 128), jnp.float32),   # planned, stays device
              jnp.ones((8,), jnp.float32)]         # unplanned tail
    wire_sizes = []

    def reduce_fn(tensors, phase):
        wire_sizes.append(sum(t.size for t in tensors))
        assert all(isinstance(t, np.ndarray) for t in tensors), \
            "wire tensors must be host arrays"
        return [t.copy() for t in tensors]

    out = average_with_powersgd(comp, leaves, reduce_fn, epoch=0)
    assert isinstance(out[0], jax.Array), "planned output left the device"
    assert isinstance(out[1], np.ndarray)
    # wire carried factors only: P is 256*4, then Q 128*4 + the tail 8
    assert wire_sizes == [256 * 4, 128 * 4 + 8]
    # error feedback lives on device
    assert isinstance(comp._errors[0], jax.Array)


def test_flagship_sized_epoch_is_transfer_bound():
    """On the flagship-shaped grad set the per-epoch host work must be
    bounded by the rank-r factor transfers, not the O(m*n*r) math: the
    projections/orthogonalization/reconstruction run inside three jitted
    device programs. Verified structurally (device outputs, factor-only
    wire) plus a generous wall-clock sanity bound; and trajectories must
    equal a plain-numpy golden implementation of the same algorithm."""
    rank = 4
    # the flagship's unique-parameter matrix shapes (4 shared blocks:
    # q/k/v/out 1024x1024, GEGLU wi/gate 1024x4096, wo 4096x1024, plus
    # the tied embedding) — ~50M parameters, the real per-epoch workload
    shapes = ([(1024, 1024)] * 16 + [(1024, 4096), (4096, 1024)] * 4
              + [(40292, 1024)])
    rng = np.random.RandomState(0)
    host = [rng.randn(*s).astype(np.float32) * 1e-3 for s in shapes]
    leaves = [jnp.asarray(x) for x in host]

    def reduce_fn(tensors, phase):
        return [t.copy() for t in tensors]

    comp = PowerSGDCompressor(rank=rank)
    t0 = time.monotonic()
    out = average_with_powersgd(comp, leaves, reduce_fn, epoch=0)
    jax.block_until_ready([x for x in out if isinstance(x, jax.Array)])
    dt = time.monotonic() - t0

    # golden: the same algorithm in plain numpy (single peer, mean = id)
    for x, plan in zip(out, comp.plan(host)):
        mat = host[plan.index].reshape(plan.m, plan.n)
        q0 = comp._q_for(plan, 0)
        p = orthogonalize(mat @ q0)
        approx = p @ (mat.T @ p).T
        np.testing.assert_allclose(np.asarray(x).reshape(plan.m, plan.n),
                                   approx, rtol=2e-3, atol=2e-5)
    # generous sanity bound: a 50M-param epoch through jitted device code
    # (including the one-time compile) must not look like host-loop MGS
    # over every gradient
    assert dt < 60, f"PowerSGD epoch took {dt:.1f}s"


def test_orthogonalize_zeroes_dependent_columns():
    """Rank-deficient P (e.g. near-constant gradients) must come back
    with dependent columns ZEROED — normalizing the cancellation noise
    into a garbage unit column makes P_orth non-orthogonal and the
    reconstruction over-counts the gradient (code-review r3 finding)."""
    from dalle_tpu.swarm.powersgd import _orthogonalize_dev

    rank1 = np.ones((64, 1), np.float32) @ np.array([[2., 3., 4.]],
                                                    np.float32)
    for fn in (orthogonalize, lambda p: np.asarray(_orthogonalize_dev(
            jnp.asarray(p)))):
        p = fn(rank1)
        # one unit column, the rest exactly zero
        np.testing.assert_allclose(np.linalg.norm(p[:, 0]), 1.0, rtol=1e-5)
        assert np.all(p[:, 1:] == 0.0), p[:, 1:]
        # and the basis is orthonormal-or-zero: P^T P is diag of 1s/0s
        gram = p.T @ p
        np.testing.assert_allclose(gram, np.diag([1.0, 0.0, 0.0]),
                                   atol=1e-5)


def test_reconstruction_exact_on_rank_deficient_mean():
    """A constant (rank-1) gradient averaged at rank 3 must reconstruct
    the exact mean — the old behavior inflated it by up to the rank."""
    comp = PowerSGDCompressor(rank=3)
    leaves = [jnp.full((64, 32), 2.0, jnp.float32)]

    def reduce_fn(tensors, phase):
        return [t.copy() for t in tensors]

    out = average_with_powersgd(comp, leaves, reduce_fn, epoch=0)
    np.testing.assert_allclose(np.asarray(out[0]),
                               np.full((64, 32), 2.0), rtol=1e-5)
