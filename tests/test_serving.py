"""Continuous-batching engine tests.

The load-bearing invariant: the per-slot-position rewrite of
``decode_step`` must not change numerics — a request decoded by the
engine emits EXACTLY the codes ``generate_images`` samples for the same
key/SamplingConfig. Pinned two ways: a single-slot engine (bit-identical
math, guaranteed), and a multi-slot ragged run where co-tenant slots
share the batch (XLA's batch-tiling wobble is ~1e-6 on logits; the
sampled codes stay exact for these pinned seeds).

Plus: slot recycling, KV-budget admission, metrics accounting, the
pixel-overlap worker, the HTTP front-end, and the thread-lifecycle
discipline (every serving thread daemonized AND reaped by stop()).
"""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import ServingConfig, tiny_model_config
from dalle_tpu.models.dalle import DALLE, init_params
from dalle_tpu.models.decode import (SamplingConfig, bucket_bounds,
                                     generate_images, init_cache,
                                     resolve_buckets)
from dalle_tpu.serving.engine import DecodeEngine
from dalle_tpu.serving.metrics import ServingMetrics, percentiles
from dalle_tpu.serving.pixels import PixelPipeline
from dalle_tpu.serving.scheduler import SlotScheduler, kv_bytes_per_slot
from dalle_tpu.serving.server import ServingHTTPServer

SAM = SamplingConfig(temperature=1.0, top_k=8)

# one flat-cache config + one cycle-structured (scan + wconv) config so
# both decode_step cache layouts run the per-slot path
FLAT = dict(attn_types=("axial_row", "axial_col"), depth=2)
CYCLE = dict(attn_types=("axial_row", "axial_col", "axial_row",
                         "axial_row"), depth=6, shared_block_cycle=4,
             final_conv_block=True, conv_kernel=3)


@pytest.fixture(scope="module")
def flat_setup():
    cfg = tiny_model_config(**FLAT)
    params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def cycle_setup():
    cfg = tiny_model_config(**CYCLE)
    params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
    return cfg, params


def _texts(cfg, n, seed=100):
    return [np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed + i), (cfg.text_seq_len,), 2,
        cfg.vocab_text)) for i in range(n)]


def _solo_reference(params, cfg, text, key, buckets):
    codes = generate_images(params, cfg, jnp.asarray(text[None]), key,
                            SAM, buckets=buckets)
    return np.asarray(codes)[0]


class TestEngineParity:
    def test_single_slot_matches_generate_images(self, flat_setup):
        """THE acceptance invariant: one request through the engine ==
        ``generate_images`` for the same seed, code for code. At
        n_slots=1 the per-slot step is bit-identical to the lockstep
        step (same shapes, same ops), so this can never flake."""
        cfg, params = flat_setup
        text = _texts(cfg, 1)[0]
        key = jax.random.PRNGKey(1000)
        ref = _solo_reference(params, cfg, text, key, buckets=4)
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM).start()
        try:
            got = engine.submit(text, key).result(timeout=300)
        finally:
            engine.stop()
        np.testing.assert_array_equal(got["codes"], ref)
        assert got["latency_s"] >= got["ttft_s"] >= 0

    def test_single_slot_matches_on_cycle_layout(self, cycle_setup):
        """Same invariant through the cycle-structured cache carry (the
        flagship's layout): scatter writes into the (reps, cycle, B, T,
        H*d) body + the wconv slot."""
        cfg, params = cycle_setup
        text = _texts(cfg, 1)[0]
        key = jax.random.PRNGKey(2000)
        ref = _solo_reference(params, cfg, text, key, buckets=1)
        engine = DecodeEngine(
            params, cfg,
            ServingConfig(n_slots=1, steps_per_call=4, decode_buckets=1),
            sampling=SAM).start()
        try:
            got = engine.submit(text, key).result(timeout=300)
        finally:
            engine.stop()
        np.testing.assert_array_equal(got["codes"], ref)

    def test_ragged_cotenancy_and_recycling_exact(self, flat_setup):
        """5 requests through 2 slots: admissions are ragged (mid-flight
        of other requests), every slot is recycled at least once, and
        EVERY request still emits its solo-reference codes — co-tenants
        cannot perturb each other's samples (pinned seeds)."""
        cfg, params = flat_setup
        texts = _texts(cfg, 5)
        keys = [jax.random.PRNGKey(1000 + i) for i in range(5)]
        refs = [_solo_reference(params, cfg, t, k, buckets=4)
                for t, k in zip(texts, keys)]
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=2, steps_per_call=4),
                              sampling=SAM).start()
        try:
            handles = []
            for i, (t, k) in enumerate(zip(texts, keys)):
                handles.append(engine.submit(t, k))
                time.sleep(0.01 * i)  # stagger: admission lands mid-chunk
            results = [h.result(timeout=300) for h in handles]
        finally:
            engine.stop()
        for res, ref in zip(results, refs):
            np.testing.assert_array_equal(res["codes"], ref)
        stats = engine.stats()
        assert stats["completed"] == 5
        # 5 requests > 2 slots: recycling necessarily happened
        assert stats["admitted"] == 5 and stats["n_slots"] == 2
        assert 0 < stats["mean_occupancy"] <= 1.0


class TestSchedulerAndBuckets:
    def test_engine_reuses_resolve_buckets(self, flat_setup):
        """The engine's bucket count comes FROM resolve_buckets (the
        measured generate_images policy), not a re-derivation."""
        cfg, params = flat_setup
        for n_slots in (1, 4, 8, 12):
            engine = DecodeEngine(params, cfg,
                                  ServingConfig(n_slots=n_slots))
            assert engine.n_buckets == resolve_buckets(None, n_slots)
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=4, decode_buckets=2))
        assert engine.n_buckets == resolve_buckets(2, 4) == 2

    def test_bucket_bounds_match_generate_images(self):
        # ONE definition in models/decode.py, used by BOTH the lockstep
        # scan and the engine's per-chunk visible choice
        assert bucket_bounds(32, 4) == [8, 16, 24, 32]
        assert bucket_bounds(1280, 2) == [640, 1280]
        assert bucket_bounds(32, 1) == [32]

    def test_scheduler_grant(self):
        sched = SlotScheduler(4, bytes_per_slot=100)
        assert sched.max_live == 4
        assert sched.grant(queued=10, live=0, free=4) == 4
        assert sched.grant(queued=1, live=2, free=2) == 1
        assert sched.grant(queued=0, live=2, free=2) == 0
        assert sched.grant(queued=5, live=4, free=0) == 0

    def test_scheduler_kv_budget(self):
        one_mb = 2 ** 20
        sched = SlotScheduler(8, bytes_per_slot=one_mb, kv_budget_mb=3)
        assert sched.max_live == 3
        assert sched.grant(queued=8, live=2, free=6) == 1
        # budget below one slot still admits one at a time
        assert SlotScheduler(8, one_mb, kv_budget_mb=0).max_live == 1
        # budget above n_slots clamps to n_slots
        assert SlotScheduler(2, one_mb, kv_budget_mb=100).max_live == 2

    def test_kv_bytes_per_slot_matches_cache(self, cycle_setup):
        cfg, _ = cycle_setup
        cache = init_cache(cfg, 1)
        expect = sum(a.size * a.dtype.itemsize
                     for a in jax.tree_util.tree_leaves(cache))
        assert kv_bytes_per_slot(cfg) == expect

    def test_kv_budget_caps_live_slots(self, flat_setup):
        """n_slots=4 but a budget worth ~2 slots: at most 2 requests are
        ever live, everything still completes via recycling."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=4, steps_per_call=4),
                              sampling=SAM)
        # tiny caches are ~100 KB/slot and the budget knob rounds whole
        # MB, so inject a scheduler with a synthetic 1 MB/slot size: a
        # 2 MB budget then caps live slots at 2 of the 4
        engine.scheduler = SlotScheduler(4, bytes_per_slot=2 ** 20,
                                         kv_budget_mb=2)
        assert engine.scheduler.max_live == 2
        engine.start()
        max_live_seen = 0
        try:
            handles = [engine.submit(t, jax.random.PRNGKey(i))
                       for i, t in enumerate(_texts(cfg, 4))]
            while not all(h.done() for h in handles):
                live = sum(p is not None for p in engine._slots)
                max_live_seen = max(max_live_seen, live)
                time.sleep(0.005)
            for h in handles:
                assert h.result(timeout=10)["codes"].shape == \
                    (cfg.image_seq_len,)
        finally:
            engine.stop()
        assert max_live_seen <= 2
        assert engine.stats()["completed"] == 4


class TestEngineLifecycle:
    def test_submit_validates_and_bounds(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, queue_capacity=1))
        with pytest.raises(ValueError):
            engine.submit(np.zeros(3, np.int32))
        engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        with pytest.raises(RuntimeError):     # queue full
            engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        engine.stop(drain=False)
        with pytest.raises(RuntimeError):     # stopped
            engine.submit(np.zeros(cfg.text_seq_len, np.int32))

    def test_stop_without_drain_cancels(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))
        handle = engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        engine.stop(drain=False)              # never started: cancel path
        with pytest.raises(RuntimeError, match="cancelled"):
            handle.result(timeout=5)
        assert engine.stats()["cancelled"] == 1

    def test_threads_daemonized_and_reaped(self, flat_setup):
        """The test_thread_lifecycle invariant for the serving stack:
        engine + pixel worker threads are daemons while alive and gone
        after stop()."""
        cfg, params = flat_setup
        before = set(threading.enumerate())
        pipeline = PixelPipeline(lambda codes: {"images": np.zeros(
            (2, 2, 3), np.uint8)})
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM,
                              pixel_pipeline=pipeline).start()
        handle = engine.submit(_texts(cfg, 1)[0], jax.random.PRNGKey(3))
        spawned = [t for t in threading.enumerate() if t not in before]
        assert spawned and all(t.daemon for t in spawned), \
            [t.name for t in spawned if not t.daemon]
        assert handle.result(timeout=300)["images"].shape == (2, 2, 3)
        engine.stop()                          # reaps pixel worker too
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and any(
                t.is_alive() for t in spawned):
            time.sleep(0.02)
        leaked = [t.name for t in spawned if t.is_alive()]
        assert not leaked, f"threads outlived stop(): {leaked}"


class TestPixelPipeline:
    def test_failure_fails_request_not_worker(self, flat_setup):
        cfg, params = flat_setup

        calls = {"n": 0}

        def flaky(codes):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("synthetic pixel failure")
            return {"images": np.ones((2, 2, 3), np.uint8)}

        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM,
                              pixel_pipeline=PixelPipeline(flaky)).start()
        try:
            texts = _texts(cfg, 2)
            h1 = engine.submit(texts[0], jax.random.PRNGKey(0))
            h2 = engine.submit(texts[1], jax.random.PRNGKey(1))
            with pytest.raises(RuntimeError, match="pixel stage failed"):
                h1.result(timeout=300)
            assert h2.result(timeout=300)["images"].sum() > 0
            # the failure is a FAILED request, not a completion — the
            # throughput/latency stats stay honest
            snap = engine.metrics.snapshot()
            assert snap["failed"] == 1 and snap["completed"] == 1
        finally:
            engine.stop()

    def test_stop_drains_pending_jobs(self):
        done = []
        slow = PixelPipeline(lambda codes: (time.sleep(0.05),
                                            done.append(1),
                                            {"x": 1})[-1])

        class H:
            def _resolve(self, payload):
                pass

        for _ in range(4):
            slow.submit(H(), 0, np.zeros(4, np.int32))
        slow.stop(timeout=10)
        assert len(done) == 4, "queued jobs must drain before the reap"


class TestMetrics:
    def test_percentiles(self):
        assert np.isnan(percentiles([], (50.0,))[0])
        assert percentiles([1.0], (50.0,)) == [1.0]
        p50, p95 = percentiles([float(i) for i in range(1, 101)])
        assert 50.0 <= p50 <= 51.0
        assert 95.0 <= p95 <= 96.0

    def test_request_accounting_and_jsonl(self, tmp_path):
        path = tmp_path / "serving.jsonl"
        m = ServingMetrics(n_slots=2, jsonl_path=str(path), interval_s=0.0)
        m._interval_s = 0.0001
        for rid in range(3):
            m.record_submit(rid)
            m.record_admit(rid)
            m.record_first_code(rid)
            row = m.record_complete(rid)
            assert row["latency_s"] >= row["ttft_s"] >= 0
            assert row["queue_wait_s"] >= 0
        m.record_step(live_slots=1, queue_depth=4)
        m.record_step(live_slots=2, queue_depth=0)
        snap = m.snapshot()
        assert snap["completed"] == 3 and snap["submitted"] == 3
        assert snap["mean_occupancy"] == pytest.approx(0.75)
        assert snap["mean_queue_depth"] == pytest.approx(2.0)
        assert snap["max_queue_depth"] == 4
        assert snap["img_per_s"] > 0
        time.sleep(0.001)
        m.maybe_flush()
        rows = [json.loads(line) for line in
                path.read_text().splitlines()]
        assert rows and rows[-1]["completed"] == 3

    def test_cancelled_requests_counted(self):
        m = ServingMetrics(n_slots=1)
        m.record_submit(7)
        m.record_cancelled(7)
        snap = m.snapshot()
        assert snap["cancelled"] == 1 and snap["completed"] == 0


class TestServeBench:
    @pytest.mark.slow
    def test_quick_bench_writes_valid_rows(self, tmp_path):
        """serve_bench --quick end-to-end as a subprocess (fresh JAX
        init + several compiles: minutes — slow-marked, like every
        bench path, so tier-1 stays inside its window). Validates the
        SERVE_BENCH.json row schema the driver reads; --quick numbers
        carry no perf claim."""
        import os
        import subprocess
        import sys
        from pathlib import Path

        repo = Path(__file__).resolve().parent.parent
        out = tmp_path / "SERVE_BENCH.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        res = subprocess.run(
            [sys.executable, str(repo / "scripts" / "serve_bench.py"),
             "--quick", "--out", str(out)],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=repo)
        assert res.returncode == 0, res.stdout[-3000:] + res.stderr[-2000:]
        rows = [json.loads(line) for line in
                out.read_text().splitlines()]
        modes = [r["mode"] for r in rows]
        assert modes == ["static", "engine", "summary"]
        for row in rows[:2]:
            assert row["img_per_s"] > 0
            assert "mean_occupancy" in row and "mean_queue_depth" in row
            assert "p95_latency_s" in row
        assert "speedup" in rows[2] and "p95_ok" in rows[2]


class TestHTTPServer:
    @pytest.fixture()
    def served(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=2, steps_per_call=4),
                              sampling=SAM).start()
        httpd = ServingHTTPServer(("127.0.0.1", 0), engine,
                                  request_timeout_s=300.0)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        yield cfg, engine, f"http://127.0.0.1:{httpd.server_address[1]}"
        httpd.shutdown()
        httpd.server_close()
        engine.stop()
        thread.join(timeout=10)

    def _post(self, url, payload):
        req = urllib.request.Request(
            url + "/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read())

    def test_generate_stats_healthz(self, served):
        cfg, engine, url = served
        tokens = _texts(cfg, 1)[0].tolist()
        status, body = self._post(url, {"tokens": tokens, "n_images": 2,
                                        "seed": 11})
        assert status == 200
        assert len(body["results"]) == 2
        for row in body["results"]:
            codes = np.asarray(row["codes"])
            assert codes.shape == (cfg.image_seq_len,)
            assert (codes >= 0).all() and (codes < cfg.vocab_image).all()
            assert row["latency_s"] >= row["ttft_s"]
        # the two images of one query use fold_in(seed, i): distinct
        assert body["results"][0]["codes"] != body["results"][1]["codes"]

        with urllib.request.urlopen(url + "/stats", timeout=30) as resp:
            stats = json.loads(resp.read())
        assert stats["completed"] >= 2 and stats["n_slots"] == 2
        with urllib.request.urlopen(url + "/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["ok"] is True

    def test_error_paths(self, served):
        cfg, engine, url = served
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"text": "no tokenizer configured"})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {})
        assert e.value.code == 400
        # wrong-length token vector is a 400, not a dropped connection
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"tokens": [1, 2, 3]})
        assert e.value.code == 400
        # non-numeric tokens (TypeError inside np.asarray) too
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"tokens": None})
        assert e.value.code == 400
        # out-of-range seed is a 400, not a handler crash
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"tokens": [1] * cfg.text_seq_len,
                             "seed": 2 ** 72})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url + "/nope", timeout=30)
        assert e.value.code == 404
