"""Continuous-batching engine tests.

The load-bearing invariant: the per-slot-position rewrite of
``decode_step`` must not change numerics — a request decoded by the
engine emits EXACTLY the codes ``generate_images`` samples for the same
key/SamplingConfig. Pinned two ways: a single-slot engine (bit-identical
math, guaranteed), and a multi-slot ragged run where co-tenant slots
share the batch (XLA's batch-tiling wobble is ~1e-6 on logits; the
sampled codes stay exact for these pinned seeds).

Plus: slot recycling, KV-budget admission, metrics accounting, the
pixel-overlap worker, the HTTP front-end, and the thread-lifecycle
discipline (every serving thread daemonized AND reaped by stop()).
"""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import ServingConfig, tiny_model_config
from dalle_tpu.models.dalle import DALLE, init_params
from dalle_tpu.models.decode import (SamplingConfig, bucket_bounds,
                                     generate_images, init_cache,
                                     resolve_buckets)
from dalle_tpu.serving import engine as engine_mod
from dalle_tpu.serving.engine import DecodeEngine
from dalle_tpu.serving.metrics import ServingMetrics, percentiles
from dalle_tpu.serving.pixels import PixelPipeline
from dalle_tpu.serving.scheduler import SlotScheduler, kv_bytes_per_slot
from dalle_tpu.serving.server import ServingHTTPServer

SAM = SamplingConfig(temperature=1.0, top_k=8)

# one flat-cache config + one cycle-structured (scan + wconv) config so
# both decode_step cache layouts run the per-slot path
FLAT = dict(attn_types=("axial_row", "axial_col"), depth=2)
CYCLE = dict(attn_types=("axial_row", "axial_col", "axial_row",
                         "axial_row"), depth=6, shared_block_cycle=4,
             final_conv_block=True, conv_kernel=3)


@pytest.fixture(scope="module")
def flat_setup():
    cfg = tiny_model_config(**FLAT)
    params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def cycle_setup():
    cfg = tiny_model_config(**CYCLE)
    params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
    return cfg, params


def _texts(cfg, n, seed=100):
    return [np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed + i), (cfg.text_seq_len,), 2,
        cfg.vocab_text)) for i in range(n)]


def _solo_reference(params, cfg, text, key, buckets):
    codes = generate_images(params, cfg, jnp.asarray(text[None]), key,
                            SAM, buckets=buckets)
    return np.asarray(codes)[0]


class TestEngineParity:
    def test_single_slot_matches_generate_images(self, flat_setup):
        """THE acceptance invariant: one request through the engine ==
        ``generate_images`` for the same seed, code for code. At
        n_slots=1 the per-slot step is bit-identical to the lockstep
        step (same shapes, same ops), so this can never flake."""
        cfg, params = flat_setup
        text = _texts(cfg, 1)[0]
        key = jax.random.PRNGKey(1000)
        ref = _solo_reference(params, cfg, text, key, buckets=4)
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM).start()
        try:
            got = engine.submit(text, key).result(timeout=300)
        finally:
            engine.stop()
        np.testing.assert_array_equal(got["codes"], ref)
        assert got["latency_s"] >= got["ttft_s"] >= 0

    def test_single_slot_matches_on_cycle_layout(self, cycle_setup):
        """Same invariant through the cycle-structured cache carry (the
        flagship's layout): scatter writes into the (reps, cycle, B, T,
        H*d) body + the wconv slot."""
        cfg, params = cycle_setup
        text = _texts(cfg, 1)[0]
        key = jax.random.PRNGKey(2000)
        ref = _solo_reference(params, cfg, text, key, buckets=1)
        engine = DecodeEngine(
            params, cfg,
            ServingConfig(n_slots=1, steps_per_call=4, decode_buckets=1),
            sampling=SAM).start()
        try:
            got = engine.submit(text, key).result(timeout=300)
        finally:
            engine.stop()
        np.testing.assert_array_equal(got["codes"], ref)

    def test_ragged_cotenancy_and_recycling_exact(self, flat_setup):
        """5 requests through 2 slots: admissions are ragged (mid-flight
        of other requests), every slot is recycled at least once, and
        EVERY request still emits its solo-reference codes — co-tenants
        cannot perturb each other's samples (pinned seeds)."""
        cfg, params = flat_setup
        texts = _texts(cfg, 5)
        keys = [jax.random.PRNGKey(1000 + i) for i in range(5)]
        refs = [_solo_reference(params, cfg, t, k, buckets=4)
                for t, k in zip(texts, keys)]
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=2, steps_per_call=4),
                              sampling=SAM).start()
        try:
            handles = []
            for i, (t, k) in enumerate(zip(texts, keys)):
                handles.append(engine.submit(t, k))
                time.sleep(0.01 * i)  # stagger: admission lands mid-chunk
            results = [h.result(timeout=300) for h in handles]
        finally:
            engine.stop()
        for res, ref in zip(results, refs):
            np.testing.assert_array_equal(res["codes"], ref)
        stats = engine.stats()
        assert stats["completed"] == 5
        # 5 requests > 2 slots: recycling necessarily happened
        assert stats["admitted"] == 5 and stats["n_slots"] == 2
        assert 0 < stats["mean_occupancy"] <= 1.0


class TestSchedulerAndBuckets:
    def test_engine_reuses_resolve_buckets(self, flat_setup):
        """The engine's bucket count comes FROM resolve_buckets (the
        measured generate_images policy), not a re-derivation."""
        cfg, params = flat_setup
        for n_slots in (1, 4, 8, 12):
            engine = DecodeEngine(params, cfg,
                                  ServingConfig(n_slots=n_slots))
            assert engine.n_buckets == resolve_buckets(None, n_slots)
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=4, decode_buckets=2))
        assert engine.n_buckets == resolve_buckets(2, 4) == 2

    def test_bucket_bounds_match_generate_images(self):
        # ONE definition in models/decode.py, used by BOTH the lockstep
        # scan and the engine's per-chunk visible choice
        assert bucket_bounds(32, 4) == [8, 16, 24, 32]
        assert bucket_bounds(1280, 2) == [640, 1280]
        assert bucket_bounds(32, 1) == [32]

    def test_scheduler_grant(self):
        sched = SlotScheduler(4, bytes_per_slot=100)
        assert sched.max_live == 4
        assert sched.grant(queued=10, live=0, free=4) == 4
        assert sched.grant(queued=1, live=2, free=2) == 1
        assert sched.grant(queued=0, live=2, free=2) == 0
        assert sched.grant(queued=5, live=4, free=0) == 0

    def test_scheduler_admit_burst(self):
        """admit_burst caps the PER-BOUNDARY batch: a cold start against
        a deep queue admits over several chunk boundaries instead of one
        outsized scatter."""
        sched = SlotScheduler(8, bytes_per_slot=100, admit_burst=2)
        assert sched.grant(queued=10, live=0, free=8) == 2
        assert sched.grant(queued=1, live=0, free=8) == 1
        # the burst never lifts the other caps
        assert sched.grant(queued=10, live=7, free=1) == 1
        assert SlotScheduler(8, 100, admit_burst=None).grant(10, 0, 8) == 8

    def test_scheduler_kv_budget(self):
        one_mb = 2 ** 20
        sched = SlotScheduler(8, bytes_per_slot=one_mb, kv_budget_mb=3)
        assert sched.max_live == 3
        assert sched.grant(queued=8, live=2, free=6) == 1
        # budget below one slot still admits one at a time
        assert SlotScheduler(8, one_mb, kv_budget_mb=0).max_live == 1
        # budget above n_slots clamps to n_slots
        assert SlotScheduler(2, one_mb, kv_budget_mb=100).max_live == 2

    def test_kv_bytes_per_slot_matches_cache(self, cycle_setup):
        cfg, _ = cycle_setup
        cache = init_cache(cfg, 1)
        expect = sum(a.size * a.dtype.itemsize
                     for a in jax.tree_util.tree_leaves(cache))
        assert kv_bytes_per_slot(cfg) == expect

    def test_kv_budget_caps_live_slots(self, flat_setup):
        """n_slots=4 but a budget worth ~2 slots: at most 2 requests are
        ever live, everything still completes via recycling."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=4, steps_per_call=4),
                              sampling=SAM)
        # tiny caches are ~100 KB/slot and the budget knob rounds whole
        # MB, so inject a scheduler with a synthetic 1 MB/slot size: a
        # 2 MB budget then caps live slots at 2 of the 4
        engine.scheduler = SlotScheduler(4, bytes_per_slot=2 ** 20,
                                         kv_budget_mb=2)
        assert engine.scheduler.max_live == 2
        engine.start()
        max_live_seen = 0
        try:
            handles = [engine.submit(t, jax.random.PRNGKey(i))
                       for i, t in enumerate(_texts(cfg, 4))]
            while not all(h.done() for h in handles):
                live = sum(p is not None for p in engine._slots)
                max_live_seen = max(max_live_seen, live)
                time.sleep(0.005)
            for h in handles:
                assert h.result(timeout=10)["codes"].shape == \
                    (cfg.image_seq_len,)
        finally:
            engine.stop()
        assert max_live_seen <= 2
        assert engine.stats()["completed"] == 4


class TestEngineLifecycle:
    def test_submit_validates_and_bounds(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, queue_capacity=1))
        with pytest.raises(ValueError):
            engine.submit(np.zeros(3, np.int32))
        engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        with pytest.raises(RuntimeError):     # queue full
            engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        engine.stop(drain=False)
        with pytest.raises(RuntimeError):     # stopped
            engine.submit(np.zeros(cfg.text_seq_len, np.int32))

    def test_stop_without_drain_cancels(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))
        handle = engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        engine.stop(drain=False)              # never started: cancel path
        with pytest.raises(RuntimeError, match="cancelled"):
            handle.result(timeout=5)
        assert engine.stats()["cancelled"] == 1

    def test_threads_daemonized_and_reaped(self, flat_setup):
        """The test_thread_lifecycle invariant for the serving stack:
        engine + pixel worker threads are daemons while alive and gone
        after stop()."""
        cfg, params = flat_setup
        before = set(threading.enumerate())
        pipeline = PixelPipeline(lambda codes: {"images": np.zeros(
            (2, 2, 3), np.uint8)})
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM,
                              pixel_pipeline=pipeline).start()
        handle = engine.submit(_texts(cfg, 1)[0], jax.random.PRNGKey(3))
        spawned = [t for t in threading.enumerate() if t not in before]
        assert spawned and all(t.daemon for t in spawned), \
            [t.name for t in spawned if not t.daemon]
        assert handle.result(timeout=300)["images"].shape == (2, 2, 3)
        engine.stop()                          # reaps pixel worker too
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and any(
                t.is_alive() for t in spawned):
            time.sleep(0.02)
        leaked = [t.name for t in spawned if t.is_alive()]
        assert not leaked, f"threads outlived stop(): {leaked}"


class TestHotLoop:
    """The r9 zero-sync loop's three load-bearing properties: one chunk
    executable serves every SamplingConfig, the device state is donated
    (the KV cache updates in place), and a novel temperature mid-run
    compiles nothing."""

    def test_chunk_executable_shared_across_sampling(self, flat_setup):
        """Two engines at different temperatures share ONE chunk
        executable: sampling knobs are traced operands, not compile
        keys — `_chunk_fn`'s lru key is (cfg, chunk, visible) and the
        underlying jit cache grows only with shapes/buckets."""
        cfg, params = flat_setup
        engine_mod._chunk_fn.cache_clear()
        text = _texts(cfg, 1)[0]

        def run_one(sampling, seed):
            engine = DecodeEngine(
                params, cfg, ServingConfig(n_slots=1, steps_per_call=4),
                sampling=sampling).start()
            try:
                return engine.submit(
                    text, jax.random.PRNGKey(seed)).result(timeout=300)
            finally:
                engine.stop()

        run_one(SamplingConfig(temperature=1.0, top_k=8), 0)
        info1 = engine_mod._chunk_fn.cache_info()
        bounds = bucket_bounds(cfg.total_seq_len, resolve_buckets(None, 1))
        sizes1 = {v: engine_mod._chunk_fn(cfg, 4, v)._cache_size()
                  for v in bounds}
        run_one(SamplingConfig(temperature=0.31, top_k=0, top_p=0.9), 1)
        info2 = engine_mod._chunk_fn.cache_info()
        sizes2 = {v: engine_mod._chunk_fn(cfg, 4, v)._cache_size()
                  for v in bounds}
        assert info2.misses == info1.misses, (
            "a second SamplingConfig built a NEW chunk program")
        assert sizes2 == sizes1, (
            f"a second SamplingConfig triggered XLA compiles: "
            f"{sizes1} -> {sizes2}")

    def test_temperature_change_midrun_zero_compiles(self, flat_setup):
        """A novel per-request temperature on a RUNNING engine triggers
        zero new compiles (the recompile-per-temperature wall the
        ROADMAP named)."""
        cfg, params = flat_setup
        texts = _texts(cfg, 2)
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=2, steps_per_call=4),
                              sampling=SAM).start()
        try:
            engine.submit(texts[0], jax.random.PRNGKey(0)).result(
                timeout=300)
            sizes1 = {v: engine_mod._chunk_fn(cfg, 4, v)._cache_size()
                      for v in engine._bounds}
            novel = SamplingConfig(temperature=0.427, top_k=5, top_p=0.8)
            engine.submit(texts[1], jax.random.PRNGKey(1),
                          sampling=novel).result(timeout=300)
            sizes2 = {v: engine_mod._chunk_fn(cfg, 4, v)._cache_size()
                      for v in engine._bounds}
        finally:
            engine.stop()
        assert sizes2 == sizes1, (
            f"novel temperature compiled: {sizes1} -> {sizes2}")

    def test_chunk_donates_state_buffers(self, flat_setup):
        """donate_argnums is live: the input EngineState's buffers (the
        KV cache above all) are DELETED after a chunk — the cache
        updates in place instead of reallocating per chunk."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=2, steps_per_call=2))
        old = engine._state
        fn = engine_mod._chunk_fn(cfg, 2, cfg.total_seq_len)
        engine._state = fn(params, old)
        jax.block_until_ready(engine._state.pos)
        donated = [old.pos, old.tokens, old.codes,
                   *jax.tree_util.tree_leaves(old.cache)]
        assert all(buf.is_deleted() for buf in donated), (
            "chunk inputs survived the call: donation is not happening")

    def test_admit_donates_and_batches(self, flat_setup):
        """Batched admission initializes K slots in ONE jitted call
        (a (K,) slot vector + (K, text_len) prefix block), also with
        the state donated."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=4, steps_per_call=2))
        texts = _texts(cfg, 3)
        keys = [np.asarray(jax.random.PRNGKey(i), np.uint32)
                for i in range(3)]
        pendings = [engine_mod._Pending(
            i, np.asarray(t, np.int32), k,
            engine_mod.RequestHandle(i), SamplingConfig(1.0, 8, 1.0))
            for i, (t, k) in enumerate(zip(texts, keys))]
        old = engine._state
        engine._admit_batch(pendings, [0, 2, 3])
        jax.block_until_ready(engine._state.pos)
        assert old.pos.is_deleted(), "admission did not donate the state"
        pos = np.asarray(engine._state.pos)
        assert pos[0] == 0 and pos[2] == 0 and pos[3] == 0
        assert pos[1] == cfg.total_seq_len       # untouched slot
        np.testing.assert_array_equal(
            np.asarray(engine._state.text)[[0, 2, 3]], np.stack(texts))
        np.testing.assert_array_equal(np.asarray(engine._state.temp),
                                      [1.0, 1.0, 1.0, 1.0])
        assert engine._pos_host[0] == 0 and engine._pos_host[1] == \
            cfg.total_seq_len

    def test_per_request_sampling_cotenancy_exact(self, flat_setup):
        """Per-request SamplingConfig end to end: three co-tenant
        requests with THREE different configs (the engine default, a
        greedy override, a top-p override) each reproduce their own
        generate_images solo reference exactly — one executable, three
        knob settings in flight at once."""
        cfg, params = flat_setup
        texts = _texts(cfg, 3)
        keys = [jax.random.PRNGKey(500 + i) for i in range(3)]
        sams = [SAM, SamplingConfig(temperature=0.0),
                SamplingConfig(temperature=1.0, top_k=0, top_p=0.7)]
        refs = [np.asarray(generate_images(
            params, cfg, jnp.asarray(t[None]), k, s, buckets=4))[0]
            for t, k, s in zip(texts, keys, sams)]
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=2, steps_per_call=4),
                              sampling=SAM).start()
        try:
            handles = [
                engine.submit(texts[0], keys[0]),           # default SAM
                engine.submit(texts[1], keys[1], sampling=sams[1]),
                engine.submit(texts[2], keys[2], sampling=sams[2]),
            ]
            results = [h.result(timeout=300) for h in handles]
        finally:
            engine.stop()
        for res, ref in zip(results, refs):
            np.testing.assert_array_equal(res["codes"], ref)

    def test_submit_rejects_bad_sampling(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))
        text = np.zeros(cfg.text_seq_len, np.int32)
        with pytest.raises(ValueError, match="temperature"):
            engine.submit(text, sampling=SamplingConfig(temperature=-1.0))
        with pytest.raises(ValueError, match="temperature"):
            # inf collapses the finite segment-vocab mask: wrong-segment
            # (negative) codes with no error — must be refused up front
            engine.submit(text,
                          sampling=SamplingConfig(temperature=float("inf")))
        with pytest.raises(ValueError, match="top_k"):
            engine.submit(text, sampling=SamplingConfig(top_k=-2))
        with pytest.raises(ValueError, match="top_k"):
            # the Python API must reject what HTTP rejects: a truncated
            # 3.9 would serve different sampling than requested
            engine.submit(text, sampling=SamplingConfig(top_k=3.9))
        with pytest.raises(ValueError, match="top_p"):
            engine.submit(text, sampling=SamplingConfig(top_p=0.0))
        engine.stop(drain=False)

    def test_bad_engine_default_fails_at_construction(self, flat_setup):
        """An invalid engine-wide default dies at construction (operator
        misconfiguration), not as a 400 on every client request."""
        cfg, params = flat_setup
        with pytest.raises(ValueError, match="temperature"):
            DecodeEngine(params, cfg, ServingConfig(n_slots=1),
                         sampling=SamplingConfig(temperature=-1.0))

    def test_crash_mid_admission_cancels_popped_requests(self, flat_setup):
        """A request popped from the queue but not yet in _slots when
        the loop crashes must still resolve (the registry catch-all) —
        a client in result() must never hang on a dead engine."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))

        def boom(admitted, slots):
            raise RuntimeError("synthetic admission crash")

        engine._admit_batch = boom
        engine.start()
        handle = engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        with pytest.raises(RuntimeError, match="cancelled"):
            handle.result(timeout=30)
        engine.stop(drain=False)
        assert engine.stats()["cancelled"] == 1
        with pytest.raises(RuntimeError):      # crashed: submits refused
            engine.submit(np.zeros(cfg.text_seq_len, np.int32))


class TestDrainTimeout:
    def test_drain_timeout_resolves_abandoned_handles(self, flat_setup):
        """stop(drain=True) that hits its bound must RESOLVE the
        abandoned handles with an error payload — a client blocked in
        result() must not hang until its own timeout."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4))
        # wedge the loop: the engine thread never serves, exactly like a
        # dispatch stuck behind a hung device. 2s outlives the 0.3s
        # bounded join by 6x but ends before interpreter teardown (a
        # daemon sleeping through exit trips XLA's C++ thread-registry
        # teardown: "terminate called without an active exception")
        engine._serve_loop = lambda: time.sleep(2)
        engine.start()
        handle = engine.submit(np.zeros(cfg.text_seq_len, np.int32))
        t0 = time.monotonic()
        engine.stop(drain=True, timeout=0.3)
        with pytest.raises(RuntimeError, match="abandoned"):
            handle.result(timeout=5)
        # the client unblocked at the drain bound, not at its own timeout
        assert time.monotonic() - t0 < 5.0
        assert engine.stats()["cancelled"] == 1

    def test_abandonment_loses_to_a_real_completion(self, flat_setup):
        """First resolution wins: a handle the engine already resolved
        is NOT overwritten by the abandonment sweep (and the metrics
        ledger counts it once, as completed)."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM).start()
        try:
            handle = engine.submit(_texts(cfg, 1)[0],
                                   jax.random.PRNGKey(0))
            payload = handle.result(timeout=300)
        finally:
            engine.stop()
        assert not handle._resolve({"error": "late abandonment"})
        assert handle.result(timeout=1)["codes"].shape == \
            (cfg.image_seq_len,)
        assert payload["latency_s"] >= 0
        snap = engine.metrics.snapshot()
        assert snap["completed"] == 1 and snap["cancelled"] == 0

    def test_late_harvest_after_abandonment_skips_ledger(self, flat_setup):
        """The inverse race: the abandonment sweep won, then the wedged
        engine thread limps through a harvest — the request must NOT
        also count as completed (nor fabricate a ~0s latency row)."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))
        handle = engine_mod.RequestHandle(0)
        engine.metrics.record_submit(0)
        assert handle._resolve({"error": "abandoned"})
        engine.metrics.record_cancelled(0)
        pending = engine_mod._Pending(
            0, np.zeros(cfg.text_seq_len, np.int32),
            np.zeros(2, np.uint32), handle, SamplingConfig())
        engine._finish_harvest(
            pending, jnp.zeros((cfg.image_seq_len,), jnp.int32))
        snap = engine.metrics.snapshot()
        assert snap["cancelled"] == 1 and snap["completed"] == 0
        with pytest.raises(RuntimeError, match="abandoned"):
            handle.result(timeout=1)

    def test_pixel_worker_skips_abandoned_handles(self):
        """Same contract on the pixel path: an already-resolved handle
        is skipped entirely — no pixel work, no completed/failed count
        on top of the cancelled one."""
        m = ServingMetrics(n_slots=1)
        ran = []
        pipeline = PixelPipeline(lambda codes: (ran.append(1),
                                                {"x": 1})[-1], metrics=m)
        handle = engine_mod.RequestHandle(7)
        m.record_submit(7)
        assert handle._resolve({"error": "abandoned"})
        m.record_cancelled(7)
        pipeline.submit(handle, 7, np.zeros(4, np.int32))
        pipeline.stop(timeout=10)
        assert ran == []
        snap = m.snapshot()
        assert snap["cancelled"] == 1 and snap["completed"] == 0 \
            and snap["failed"] == 0


class TestPixelPipeline:
    def test_failure_fails_request_not_worker(self, flat_setup):
        cfg, params = flat_setup

        calls = {"n": 0}

        def flaky(codes):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("synthetic pixel failure")
            return {"images": np.ones((2, 2, 3), np.uint8)}

        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM,
                              pixel_pipeline=PixelPipeline(flaky)).start()
        try:
            texts = _texts(cfg, 2)
            h1 = engine.submit(texts[0], jax.random.PRNGKey(0))
            h2 = engine.submit(texts[1], jax.random.PRNGKey(1))
            with pytest.raises(RuntimeError, match="pixel stage failed"):
                h1.result(timeout=300)
            assert h2.result(timeout=300)["images"].sum() > 0
            # the failure is a FAILED request, not a completion — the
            # throughput/latency stats stay honest
            snap = engine.metrics.snapshot()
            assert snap["failed"] == 1 and snap["completed"] == 1
        finally:
            engine.stop()

    def test_clean_drain_completes_pixel_queued_requests(self, flat_setup):
        """stop(drain=True) with a request already handed to the pixel
        queue must COMPLETE it (decode finished; the pipeline's drain
        resolves it) — never steal it as 'cancelled at engine stop'."""
        cfg, params = flat_setup
        release = threading.Event()

        def slow_pixels(codes):
            release.wait(10)
            return {"images": np.ones((2, 2, 3), np.uint8)}

        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM,
                              pixel_pipeline=PixelPipeline(slow_pixels)
                              ).start()
        handle = engine.submit(_texts(cfg, 1)[0], jax.random.PRNGKey(4))
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline and (
                engine._slots[0] is not None or engine._harvests):
            time.sleep(0.01)       # decode done, job now pixel-queued
        stopper = threading.Thread(
            target=lambda: engine.stop(drain=True, timeout=60))
        stopper.start()
        time.sleep(0.1)            # engine loop exits while pixels wait
        release.set()
        stopper.join(60)
        assert not stopper.is_alive()
        assert handle.result(timeout=10)["images"].sum() > 0
        snap = engine.metrics.snapshot()
        assert snap["completed"] == 1 and snap["cancelled"] == 0

    def test_stop_drains_pending_jobs(self):
        done = []
        slow = PixelPipeline(lambda codes: (time.sleep(0.05),
                                            done.append(1),
                                            {"x": 1})[-1])

        class H:
            def _claim(self):
                return True

            def _deliver(self, payload):
                pass

        for _ in range(4):
            slow.submit(H(), 0, np.zeros(4, np.int32))
        slow.stop(timeout=10)
        assert len(done) == 4, "queued jobs must drain before the reap"


class TestMetrics:
    def test_percentiles(self):
        assert np.isnan(percentiles([], (50.0,))[0])
        assert percentiles([1.0], (50.0,)) == [1.0]
        p50, p95 = percentiles([float(i) for i in range(1, 101)])
        assert 50.0 <= p50 <= 51.0
        assert 95.0 <= p95 <= 96.0

    def test_request_accounting_and_jsonl(self, tmp_path):
        path = tmp_path / "serving.jsonl"
        m = ServingMetrics(n_slots=2, jsonl_path=str(path), interval_s=0.0)
        m._interval_s = 0.0001
        for rid in range(3):
            m.record_submit(rid)
            m.record_admit(rid)
            m.record_first_code(rid)
            row = m.record_complete(rid)
            assert row["latency_s"] >= row["ttft_s"] >= 0
            assert row["queue_wait_s"] >= 0
        m.record_step(live_slots=1, queue_depth=4)
        m.record_step(live_slots=2, queue_depth=0)
        snap = m.snapshot()
        assert snap["completed"] == 3 and snap["submitted"] == 3
        assert snap["mean_occupancy"] == pytest.approx(0.75)
        assert snap["mean_queue_depth"] == pytest.approx(2.0)
        assert snap["max_queue_depth"] == 4
        assert snap["img_per_s"] > 0
        time.sleep(0.001)
        m.maybe_flush()
        rows = [json.loads(line) for line in
                path.read_text().splitlines()]
        assert rows and rows[-1]["completed"] == 3

    def test_cancelled_requests_counted(self):
        m = ServingMetrics(n_slots=1)
        m.record_submit(7)
        m.record_cancelled(7)
        snap = m.snapshot()
        assert snap["cancelled"] == 1 and snap["completed"] == 0


class TestServeBench:
    @pytest.mark.slow
    def test_quick_bench_writes_valid_rows(self, tmp_path):
        """serve_bench --quick end-to-end as a subprocess (fresh JAX
        init + several compiles: minutes — slow-marked, like every
        bench path, so tier-1 stays inside its window). Validates the
        SERVE_BENCH.json row schema the driver reads; --quick numbers
        carry no perf claim."""
        import os
        import subprocess
        import sys
        from pathlib import Path

        repo = Path(__file__).resolve().parent.parent
        out = tmp_path / "SERVE_BENCH.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        res = subprocess.run(
            [sys.executable, str(repo / "scripts" / "serve_bench.py"),
             "--quick", "--out", str(out)],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=repo)
        assert res.returncode == 0, res.stdout[-3000:] + res.stderr[-2000:]
        rows = [json.loads(line) for line in
                out.read_text().splitlines()]
        modes = [r["mode"] for r in rows]
        assert modes == ["static", "engine", "summary"]
        for row in rows[:2]:
            assert row["img_per_s"] > 0
            assert "mean_occupancy" in row and "mean_queue_depth" in row
            assert "p95_latency_s" in row
        assert "speedup" in rows[2] and "p95_ok" in rows[2]


class TestEngineLoopBench:
    @pytest.mark.slow
    def test_quick_bench_writes_valid_rows(self, tmp_path):
        """engine_loop_bench --quick as a subprocess (fresh JAX init +
        two chunk-variant compiles: minutes — slow-marked like every
        bench path). Validates the ENGINE_LOOP_BENCH.json row schema;
        --quick numbers carry no perf claim."""
        import os
        import subprocess
        import sys
        from pathlib import Path

        repo = Path(__file__).resolve().parent.parent
        out = tmp_path / "ENGINE_LOOP_BENCH.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        res = subprocess.run(
            [sys.executable,
             str(repo / "scripts" / "engine_loop_bench.py"),
             "--quick", "--out", str(out)],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=repo)
        assert res.returncode == 0, res.stdout[-3000:] + res.stderr[-2000:]
        rows = [json.loads(line) for line in
                out.read_text().splitlines()]
        assert [r["mode"] for r in rows] == ["sync", "pipelined",
                                             "summary"]
        for row in rows[:2]:
            assert row["device_ms_per_chunk"] > 0
            assert row["wall_ms_per_chunk"] > 0
            assert "dispatch_gap_ms" in row
            assert "host_overhead_ms_per_chunk" in row
        assert "overhead_removed_ms_per_chunk" in rows[2]
        assert "wall_speedup" in rows[2]


class TestHTTPServer:
    @pytest.fixture()
    def served(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=2, steps_per_call=4),
                              sampling=SAM).start()
        httpd = ServingHTTPServer(("127.0.0.1", 0), engine,
                                  request_timeout_s=300.0)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        yield cfg, engine, f"http://127.0.0.1:{httpd.server_address[1]}"
        httpd.shutdown()
        httpd.server_close()
        engine.stop()
        thread.join(timeout=10)

    def _post(self, url, payload):
        req = urllib.request.Request(
            url + "/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read())

    def test_generate_stats_healthz(self, served):
        cfg, engine, url = served
        tokens = _texts(cfg, 1)[0].tolist()
        status, body = self._post(url, {"tokens": tokens, "n_images": 2,
                                        "seed": 11})
        assert status == 200
        assert len(body["results"]) == 2
        for row in body["results"]:
            codes = np.asarray(row["codes"])
            assert codes.shape == (cfg.image_seq_len,)
            assert (codes >= 0).all() and (codes < cfg.vocab_image).all()
            assert row["latency_s"] >= row["ttft_s"]
        # the two images of one query use fold_in(seed, i): distinct
        assert body["results"][0]["codes"] != body["results"][1]["codes"]

        with urllib.request.urlopen(url + "/stats", timeout=30) as resp:
            stats = json.loads(resp.read())
        assert stats["completed"] >= 2 and stats["n_slots"] == 2
        with urllib.request.urlopen(url + "/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["ok"] is True

    def test_error_paths(self, served):
        cfg, engine, url = served
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"text": "no tokenizer configured"})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {})
        assert e.value.code == 400
        # wrong-length token vector is a 400, not a dropped connection
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"tokens": [1, 2, 3]})
        assert e.value.code == 400
        # non-numeric tokens (TypeError inside np.asarray) too
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"tokens": None})
        assert e.value.code == 400
        # out-of-range seed is a 400, not a handler crash
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"tokens": [1] * cfg.text_seq_len,
                             "seed": 2 ** 72})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url + "/nope", timeout=30)
        assert e.value.code == 404
        # out-of-range per-request sampling knobs are a 400
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"tokens": [1] * cfg.text_seq_len,
                             "temperature": -0.5})
        assert e.value.code == 400
        # non-integral top_k must not silently truncate to a DIFFERENT
        # sampling config than the client asked for
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(url, {"tokens": [1] * cfg.text_seq_len,
                             "top_k": 3.9})
        assert e.value.code == 400

    def test_per_request_sampling_over_http(self, served):
        """The POST body's sampling knobs reach the engine: a greedy
        (temperature 0) request is deterministic — same seed, same
        codes — while the stochastic default keeps its own stream."""
        cfg, engine, url = served
        tokens = _texts(cfg, 1)[0].tolist()
        status, a = self._post(url, {"tokens": tokens, "seed": 3,
                                     "temperature": 0.0})
        status_b, b = self._post(url, {"tokens": tokens, "seed": 3,
                                       "temperature": 0.0})
        assert status == status_b == 200
        assert a["results"][0]["codes"] == b["results"][0]["codes"]
        ref = np.asarray(generate_images(
            engine._params, cfg,
            jnp.asarray(np.asarray(tokens, np.int32)[None]),
            jax.random.fold_in(jax.random.PRNGKey(3), 0),
            SamplingConfig(temperature=0.0), buckets=4))[0]
        np.testing.assert_array_equal(a["results"][0]["codes"], ref)

    def test_queue_full_maps_to_429(self, flat_setup):
        """submit()'s backpressure rejection is an HTTP 429 (retryable),
        NOT a generic failure: an unstarted engine with queue_capacity=1
        fills on the first sibling of a 2-image query."""
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, queue_capacity=1))
        httpd = ServingHTTPServer(("127.0.0.1", 0), engine,
                                  request_timeout_s=5.0)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                self._post(url, {"tokens": _texts(cfg, 1)[0].tolist(),
                                 "n_images": 2})
            assert e.value.code == 429
        finally:
            httpd.shutdown()
            httpd.server_close()
            engine.stop(drain=False)
            thread.join(timeout=10)

    def test_stopping_engine_maps_to_503(self, flat_setup):
        cfg, params = flat_setup
        engine = DecodeEngine(params, cfg, ServingConfig(n_slots=1))
        engine.stop(drain=False)        # engine gone before the request
        httpd = ServingHTTPServer(("127.0.0.1", 0), engine,
                                  request_timeout_s=5.0)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                self._post(url, {"tokens": _texts(cfg, 1)[0].tolist()})
            assert e.value.code == 503
        finally:
            httpd.shutdown()
            httpd.server_close()
            thread.join(timeout=10)
