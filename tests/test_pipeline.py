"""r19 pipelined butterfly: hide the collective behind compute.

Covers the per-part pipeline added in r19 (ISSUE 19):

- the bounded-depth scatter scheduler (``_scatter_pipeline``) as a unit:
  depth=1 fully serializes parts, completion launches the next part, and
  the done-event/snapshot contract holds;
- transparency: ``pipeline_hops=False`` rounds stay byte-identical to
  rounds that never pass the knob (the r18 wire);
- bit-exactness: pipelined honest rounds on the pinned u4 wire with
  error feedback produce byte-identical averages to sequential rounds,
  and leave byte-identical EF residuals;
- the r14 audit replays a PIPELINED round clean at ``frac=1.0`` — the
  out-of-order fused accumulation must replay in recorded order;
- observability: ``report["phases"]["hops"]`` rows and live
  ``ar_hop_*`` tracer spans appear in BOTH modes (satellite of r19);
- the optimizer's hop-progress plumbing (``_PendingRound.note_hop`` /
  ``round_progress``).
"""

import concurrent.futures
import threading
import time

import numpy as np
import pytest

from dalle_tpu.config import CollabConfig
from dalle_tpu.obs.trace import Tracer
from dalle_tpu.swarm import DHT, Identity, compression
from dalle_tpu.swarm.allreduce import (_scatter_pipeline, flatten_tensors,
                                       run_allreduce)
from dalle_tpu.swarm.audit import AuditPolicy, RoundAudit, audit_round
from dalle_tpu.swarm.error_feedback import make_pair
from dalle_tpu.swarm.health import PeerHealthLedger
from dalle_tpu.swarm.identity import Ed25519PrivateKey
from dalle_tpu.swarm.matchmaking import make_group

U4 = compression.UNIFORM4BIT
U8 = compression.UNIFORM8BIT


def _det_swarm(n, base=171):
    nodes = []
    for i in range(n):
        peers = [nodes[0].visible_address] if nodes else []
        ident = Identity(Ed25519PrivateKey.from_private_bytes(
            bytes([base + i]) * 32))
        nodes.append(DHT(initial_peers=peers, identity=ident,
                         rpc_timeout=2.0))
    return nodes


def _run_threads(fns, timeout=60):
    results = [None] * len(fns)
    errors = []

    def wrap(i, fn):
        try:
            results[i] = fn()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i, fn))
               for i, fn in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    if errors:
        raise errors[0]
    return results


def _round(nodes, prefix, epoch, tensors, *, pipelined, efs=None,
           codec=U4, gather_codec=None, ras=None, ledgers=None,
           tracers=None, chunk_elems=4096, explicit_off=True):
    """One full-group round; returns (results, reports). With
    ``explicit_off=False`` and ``pipelined=False`` the knob is omitted
    entirely (the pre-r19 call shape) for the transparency check."""
    n = len(nodes)
    reports = [dict() for _ in range(n)]

    def peer(i):
        g = make_group(nodes[i], prefix, epoch=epoch, weight=1.0,
                       matchmaking_time=2.0, min_group_size=n)
        assert g is not None and g.size == n
        kw = {}
        if pipelined or explicit_off:
            kw["pipeline_hops"] = pipelined
        if efs is not None:
            kw.update(ef_scatter=efs[i][0], ef_gather=efs[i][1])
        if ras is not None:
            kw["audit"] = ras[i]
        if ledgers is not None:
            kw["ledger"] = ledgers[i]
        if tracers is not None:
            kw.update(tracer=tracers[i], trace=f"{prefix}:grads:{epoch}")
        return run_allreduce(
            nodes[i], g, prefix, epoch, tensors[i], weight=1.0,
            allreduce_timeout=10.0, sender_timeout=2.0, codec=codec,
            gather_codec=gather_codec, pin_codec=True,
            chunk_elems=chunk_elems, report=reports[i], **kw)

    results = _run_threads([lambda i=i: peer(i) for i in range(n)])
    return results, reports


def _tensors(n, size=9000, seed=11):
    rng = np.random.RandomState(seed)
    return [[(rng.randn(size) * (1 + i)).astype(np.float32)]
            for i in range(n)]


# -- the bounded-depth scatter scheduler, as a unit ------------------------

class TestScatterScheduler:
    def test_empty_tasks_complete_immediately(self):
        with concurrent.futures.ThreadPoolExecutor(2) as pool:
            done, snap = _scatter_pipeline(pool, lambda: None, [], 2, None)
            assert done.is_set() and snap() == []

    def test_depth_one_serializes_parts(self):
        """depth=1: every chunk of part k completes before any chunk of
        part k+1 STARTS — part-completion is what launches the next."""
        events, lock = [], threading.Lock()

        def produce(part, chunk):
            with lock:
                events.append((part, chunk))
            time.sleep(0.002)

        tasks = [(k, [(k, c) for c in range(3)]) for k in range(4)]
        with concurrent.futures.ThreadPoolExecutor(4) as pool:
            done, snap = _scatter_pipeline(pool, produce, tasks, 1, None)
            assert done.wait(timeout=10)
        futures = snap()
        assert len(futures) == 12 and all(f.done() for f in futures)
        starts = [events.index((k, c)) for k in range(4) for c in range(3)]
        for k in range(3):
            last_of_k = max(starts[k * 3:(k + 1) * 3])
            first_of_next = min(starts[(k + 1) * 3:(k + 2) * 3])
            assert last_of_k < first_of_next, events

    def test_depth_bounds_inflight_parts(self):
        """With depth=2 and a wide pool, chunks of at most 2 distinct
        parts ever run concurrently: the scheduler admits at most
        ``depth`` incomplete parts and a new one launches only when a
        prior part's last chunk completes."""
        lock = threading.Lock()
        running, max_seen = {}, [0]

        def produce(part, _chunk):
            with lock:
                running[part] = running.get(part, 0) + 1
                live = sum(1 for c in running.values() if c > 0)
                max_seen[0] = max(max_seen[0], live)
            time.sleep(0.005)
            with lock:
                running[part] -= 1

        tasks = [(k, [(k, c) for c in range(2)]) for k in range(5)]
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            done, _snap = _scatter_pipeline(pool, produce, tasks, 2, None)
            assert done.wait(timeout=10)
        assert max_seen[0] <= 2, max_seen[0]

    def test_on_part_fires_once_per_part(self):
        calls, lock = [], threading.Lock()

        def on_part(leg, part):
            with lock:
                calls.append((leg, part))

        tasks = [(k, [(k, c) for c in range(2)]) for k in range(3)]
        with concurrent.futures.ThreadPoolExecutor(3) as pool:
            done, _ = _scatter_pipeline(
                pool, lambda *_a: time.sleep(0.001), tasks, 2, on_part)
            assert done.wait(timeout=10)
        assert sorted(calls) == [("scatter", 0), ("scatter", 1),
                                 ("scatter", 2)]


# -- transparency + bit-exactness ------------------------------------------

class TestPipelinedRound:
    def test_off_is_byte_identical_to_pre_change_call(self):
        """pipeline_hops=False must be indistinguishable from never
        passing the knob: same bytes out of the same inputs."""
        nodes = _det_swarm(2, base=141)
        try:
            tensors = _tensors(2, size=5000, seed=3)
            res_a, _ = _round(nodes, "off-a", 0, tensors,
                              pipelined=False, explicit_off=False)
            res_b, _ = _round(nodes, "off-b", 1, tensors,
                              pipelined=False, explicit_off=True)
            for a, b in zip(res_a, res_b):
                assert flatten_tensors(a).tobytes() == \
                    flatten_tensors(b).tobytes()
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_pipelined_bit_exact_u4_ef(self):
        """Pipelined honest rounds on the pinned u4 wire with error
        feedback: byte-identical averages AND byte-identical EF
        residuals vs the sequential protocol (fresh EF per mode, same
        gradients — only the scheduling differs).

        The gradients are integers in [-7, 7] with every u4 block's
        max forced to 7 (scale exactly 1.0, dequantize exact), so the
        fused accumulation is order-INDEPENDENT: the comparison
        isolates the pipeline's arithmetic from the pre-existing
        arrival-order f32 nondeterminism that both modes share (and
        that the r14 audit covers by replaying in recorded order)."""
        nodes = _det_swarm(3, base=151)
        try:
            rng = np.random.RandomState(7)
            tensors = []
            for i in range(3):
                g = rng.randint(-7, 8, size=9000).astype(np.float32)
                g[::128] = 7.0  # every 1024-block hits max|x| == 7
                tensors.append([g])
            efs_seq = [make_pair() for _ in range(3)]
            efs_pip = [make_pair() for _ in range(3)]
            res_s, reps_s = _round(nodes, "bx", 0, tensors,
                                   pipelined=False, efs=efs_seq,
                                   gather_codec=U4)
            res_p, reps_p = _round(nodes, "bx", 1, tensors,
                                   pipelined=True, efs=efs_pip,
                                   gather_codec=U4)
            assert all(r["complete"] for r in reps_s + reps_p)
            flats = [flatten_tensors(r) for r in res_s + res_p]
            for f in flats[1:]:
                assert flats[0].tobytes() == f.tobytes()
            # identical residuals: the pipeline reordered WORK, not math
            for (ss, sg), (ps, pg) in zip(efs_seq, efs_pip):
                for seq_ef, pip_ef in ((ss, ps), (sg, pg)):
                    rs, rp = (seq_ef.residual_host(),
                              pip_ef.residual_host())
                    if rs is None:
                        assert rp is None
                    else:
                        assert rs.tobytes() == rp.tobytes()
            # the feedback loop is LIVE on the gather leg: averages are
            # thirds, so re-quantizing them has genuinely nonzero error
            # (the scatter leg is exact by construction here)
            assert any(ga.residual_host() is not None
                       and np.abs(ga.residual_host()).max() > 0
                       for _sc, ga in efs_pip)
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_pipelined_round_replays_clean_under_full_audit(self):
        """frac=1.0 audit over a PIPELINED u8/u4+EF round: out-of-order
        part completion must still post transcripts before first serve
        and replay bit-exactly — zero strikes for honest owners."""
        nodes = _det_swarm(3, base=161)
        efs = [make_pair() for _ in range(3)]
        policy = AuditPolicy(frac=1.0, fetch_timeout=2.0)
        try:
            for epoch in (0, 1):  # live residuals by the second round
                tensors = _tensors(3, size=6000, seed=20 + epoch)
                ras = [RoundAudit("pa", epoch, policy) for _ in range(3)]
                ledgers = [PeerHealthLedger() for _ in range(3)]
                res, reps = _round(nodes, "pa", epoch, tensors,
                                   pipelined=True, efs=efs, codec=U8,
                                   gather_codec=U4, ras=ras,
                                   ledgers=ledgers)
                assert all(r["complete"] for r in reps)
                for i in range(3):
                    rep = audit_round(nodes[i], ras[i], ledgers[i])
                    assert rep["audited"], (epoch, i, rep)
                    assert not rep["failed"] and not rep["unserved"] \
                        and not rep["omitted"], (epoch, i, rep)
                    assert ledgers[i].snapshot() == {}
                flats = [flatten_tensors(r) for r in res]
                for f in flats[1:]:
                    assert flats[0].tobytes() == f.tobytes()
        finally:
            for nd in nodes:
                nd.shutdown()


# -- r20 deterministic pipelined reduction ---------------------------------

class TestDeterministicPipelinedReduction:
    """r20: the gather drain lands contributions in arrival order, but
    the owner folds them at the round seam in roster-index order — so a
    pipelined round's bytes are a pure function of (roster, inputs,
    codec), reproducible across independent runs, and the transcript's
    recorded applied order is roster-derived by construction."""

    def _one_run(self, base, prefix, *, pipelined, ras=None,
                 ledgers=None):
        nodes = _det_swarm(3, base=base)
        try:
            # float wire (NONE codec): f32 accumulation is genuinely
            # order-SENSITIVE here, unlike the integer-exact u4 setup
            # above — arrival-order folding would make two runs of the
            # same schedule disagree whenever the drain reorders
            tensors = _tensors(3, size=9000, seed=13)
            res, reps = _round(nodes, prefix, 0, tensors,
                               pipelined=pipelined,
                               codec=compression.NONE, ras=ras,
                               ledgers=ledgers)
            assert all(r["complete"] for r in reps)
            return [flatten_tensors(r).tobytes() for r in res]
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_two_independent_runs_bit_identical(self):
        """Same identities, same inputs, fresh swarm each time: every
        member's pipelined round bytes match across the two runs (and
        across members within a run)."""
        run_a = self._one_run(191, "det-a", pipelined=True)
        run_b = self._one_run(191, "det-b", pipelined=True)
        assert len(set(run_a)) == 1  # members agree within a run
        assert run_a == run_b        # and across runs, bit-exactly

    def test_pipelined_float_round_replays_clean(self):
        """The roster-order fold is what the transcript records: a
        frac=1.0 audit of a float-codec PIPELINED round replays every
        honest owner bit-exactly (the recorded-order contract, now a
        roster-pinned invariant)."""
        policy = AuditPolicy(frac=1.0, fetch_timeout=2.0)
        ras = [RoundAudit("det-r", 0, policy) for _ in range(3)]
        ledgers = [PeerHealthLedger() for _ in range(3)]
        nodes = _det_swarm(3, base=201)
        try:
            tensors = _tensors(3, size=9000, seed=13)
            _res, reps = _round(nodes, "det-r", 0, tensors,
                                pipelined=True, codec=compression.NONE,
                                ras=ras, ledgers=ledgers)
            assert all(r["complete"] for r in reps)
            for i in range(3):
                rep = audit_round(nodes[i], ras[i], ledgers[i])
                assert rep["audited"], (i, rep)
                assert not rep["failed"] and not rep["unserved"] \
                    and not rep["omitted"], (i, rep)
                assert ledgers[i].snapshot() == {}
                # the applied order the transcript recorded is the
                # roster order — pinned, not incidental
                assert ras[i].order == sorted(ras[i].order), \
                    (i, ras[i].order)
        finally:
            for nd in nodes:
                nd.shutdown()


# -- observability: hop rows + spans ---------------------------------------

class TestHopObservability:
    def test_hop_rows_and_spans_both_modes(self):
        nodes = _det_swarm(3, base=181)
        try:
            tensors = _tensors(3, size=9000, seed=5)
            for epoch, pipelined in ((0, False), (1, True)):
                tracers = [Tracer(peer=f"p{i}") for i in range(3)]
                _res, reps = _round(nodes, "obs", epoch, tensors,
                                    pipelined=pipelined, tracers=tracers)
                for i, rep in enumerate(reps):
                    hops = rep["phases"].get("hops")
                    assert hops, (pipelined, i, rep["phases"])
                    for row in hops:
                        assert {"part", "leg", "wall_s", "bytes",
                                "chunks"} <= set(row)
                        assert row["wall_s"] >= 0 and row["chunks"] >= 1
                    legs = {r["leg"] for r in hops}
                    assert {"scatter", "reduce"} <= legs, (pipelined,
                                                           legs)
                    assert legs & {"gather", "gather_serve"}, legs
                    phases = {row["phase"] for row in tracers[i].dump()}
                    assert any(p.startswith("ar_hop_") for p in phases), \
                        (pipelined, phases)
        finally:
            for nd in nodes:
                nd.shutdown()


# -- optimizer plumbing ----------------------------------------------------

class TestProgressPlumbing:
    def test_config_defaults_off(self):
        cfg = CollabConfig()
        assert cfg.pipeline_hops is False
        assert cfg.pipeline_depth == 2

    def test_pending_round_hop_counters(self):
        from dalle_tpu.swarm.optimizer import _PendingRound
        p = _PendingRound(0, None, [], 1.0, 1)
        assert p.hop_progress() == {"scatter": 0, "reduce": 0,
                                    "gather": 0}
        p.note_hop("scatter", 0)
        p.note_hop("scatter", 1)
        p.note_hop("gather", 2)
        p.note_hop("bogus-leg", 0)  # unknown legs are dropped, not kept
        prog = p.hop_progress()
        assert prog == {"scatter": 2, "reduce": 0, "gather": 1}
        prog["scatter"] = 99  # a copy, not the live dict
        assert p.hop_progress()["scatter"] == 2

    def test_round_progress_none_without_pending(self):
        import dataclasses

        from dalle_tpu.swarm.optimizer import CollaborativeOptimizer

        class _S:
            params = {"w": np.zeros(4, np.float32)}
            opt_state = ()

        class _Role:
            swarm_enabled = False

        cfg = dataclasses.replace(CollabConfig(), pipeline_hops=True)
        opt = CollaborativeOptimizer(None, cfg, _S(), lambda s, g: s,
                                     serve_state=False, role=_Role())
        assert opt._pipeline_hops is True
        assert opt._pipeline_depth == 2
        assert opt.round_progress() is None
