"""Data pipeline tests: tokenizer round-trips, shard streaming, filters,
collation, and an end-to-end train-from-disk loop."""

import numpy as np
import pytest

from dalle_tpu.config import tiny_model_config
from dalle_tpu.data.dataset import (CodesDataset, decode_codes,
                                    record_filter, write_shard)
from dalle_tpu.data.tokenizer import CaptionTokenizer

CAPTIONS = [
    "a red cat sitting on a blue boat",
    "tiny dog under a large green tree",
    "a painting of a house near the mountain",
    "photo of the sky above the sea",
    "the quick brown fox jumps over the lazy dog",
    "a blue tree and a red sky",
]


@pytest.fixture(scope="module")
def tokenizer(tmp_path_factory):
    path = tmp_path_factory.mktemp("tok") / "tokenizer.json"
    tok = CaptionTokenizer.train(CAPTIONS * 20, vocab_size=200,
                                 save_path=str(path))
    return tok


class TestTokenizer:
    def test_specials_layout(self, tokenizer):
        assert tokenizer.pad_id == 0
        assert tokenizer.eos_id == 1
        assert tokenizer.vocab_size <= 200

    def test_roundtrip(self, tokenizer):
        for text in CAPTIONS:
            ids, mask = tokenizer.encode(text, max_len=64)
            n = int(mask.sum())
            assert ids[n - 1] == tokenizer.eos_id
            assert (ids[n:] == tokenizer.pad_id).all()
            assert tokenizer.decode(ids) == text

    def test_truncation(self, tokenizer):
        ids, mask = tokenizer.encode(" ".join(CAPTIONS), max_len=8)
        assert ids.shape == (8,)
        assert ids[7] == tokenizer.eos_id and mask.sum() == 8

    def test_save_load_identical(self, tokenizer, tmp_path):
        path = tmp_path / "t.json"
        tokenizer.save(str(path))
        loaded = CaptionTokenizer.load(str(path))
        ids_a, _ = tokenizer.encode(CAPTIONS[0], 32)
        ids_b, _ = loaded.encode(CAPTIONS[0], 32)
        np.testing.assert_array_equal(ids_a, ids_b)


class TestFilters:
    def test_reference_filters(self):
        ok = {"caption": "a cat", "NSFW": "UNLIKELY",
              "width": 512, "height": 384}
        assert record_filter(ok)
        assert not record_filter({**ok, "caption": "ab"})       # too short
        assert not record_filter({**ok, "NSFW": "NSFW"})        # nsfw
        assert not record_filter({**ok, "width": 1200, "height": 300})
        assert record_filter({"caption": "a cat"})              # fields absent

    def test_code_decoding(self):
        codes = np.arange(16, dtype="<i2")
        rec = {"codes": codes.tobytes()}
        out = decode_codes(rec, 16)
        np.testing.assert_array_equal(out, np.arange(16))
        assert out.dtype == np.int32
        assert decode_codes(rec, 32) is None  # wrong length


def _make_shards(tmp_path, cfg, n_shards=2, per_shard=40, seed=0):
    rng = np.random.default_rng(seed)
    kept = 0
    for s in range(n_shards):
        records = []
        for i in range(per_shard):
            records.append({
                "caption": CAPTIONS[int(rng.integers(len(CAPTIONS)))],
                "codes": rng.integers(0, cfg.vocab_image,
                                      cfg.image_seq_len).astype("<i2"),
                "NSFW": "UNLIKELY", "width": 256, "height": 256})
        # one bad record per shard: must be filtered, not crash
        records.append({"caption": "x", "codes": b""})
        kept += per_shard
        write_shard(str(tmp_path / f"shard_{s}.msgpack"), records)
    return kept


class TestCodesDataset:
    def test_batches_shapes_and_mask(self, tmp_path, tokenizer):
        cfg = tiny_model_config()
        _make_shards(tmp_path, cfg)
        ds = CodesDataset(str(tmp_path), cfg, tokenizer=tokenizer,
                          shuffle_buffer=16)
        batch = next(ds.batches(4, seed=1))
        assert batch["text"].shape == (4, cfg.text_seq_len)
        assert batch["image"].shape == (4, cfg.image_seq_len)
        assert batch["mask"].shape == (4, cfg.total_seq_len)
        # image positions always count toward the loss
        assert (batch["mask"][:, cfg.text_seq_len:] == 1).all()
        # caption padding masked out, at least eos real, padding only at
        # the tail (rows may be full when the caption truncates)
        text_mask = batch["mask"][:, : cfg.text_seq_len]
        assert (text_mask.sum(1) >= 1).all()
        assert (np.diff(text_mask, axis=1) <= 0).all()
        assert (batch["image"] >= 0).all()
        assert (batch["image"] < cfg.vocab_image).all()

    def test_per_peer_seeds_diverge(self, tmp_path, tokenizer):
        cfg = tiny_model_config()
        _make_shards(tmp_path, cfg, n_shards=1, per_shard=64)
        ds = CodesDataset(str(tmp_path), cfg, tokenizer=tokenizer,
                          shuffle_buffer=32)
        b1 = next(ds.batches(8, seed=1))
        b2 = next(ds.batches(8, seed=2))
        assert not np.array_equal(b1["image"], b2["image"])

    def test_non_loop_exhausts(self, tmp_path, tokenizer):
        cfg = tiny_model_config()
        kept = _make_shards(tmp_path, cfg, n_shards=1, per_shard=20)
        ds = CodesDataset(str(tmp_path), cfg, tokenizer=tokenizer,
                          shuffle_buffer=8)
        batches = list(ds.batches(4, seed=0, loop=False))
        assert len(batches) == kept // 4

    def test_train_from_disk_loss_drops(self, tmp_path, tokenizer):
        """End-to-end: a tiny model trains from shard files on disk and the
        loss falls (VERDICT r1 'Next round' item 4)."""
        import jax

        from dalle_tpu.config import OptimizerConfig
        from dalle_tpu.models.dalle import DALLE, init_params
        from dalle_tpu.optim import make_optimizer
        from dalle_tpu.training.steps import TrainState, make_train_step

        cfg = tiny_model_config(vocab_text=256)
        _make_shards(tmp_path, cfg, n_shards=1, per_shard=32)
        ds = CodesDataset(str(tmp_path), cfg, tokenizer=tokenizer,
                          shuffle_buffer=8)
        model = DALLE(cfg)
        params = init_params(model, jax.random.PRNGKey(0))
        tx = make_optimizer(OptimizerConfig(
            learning_rate=3e-3, warmup_steps=2, total_steps=100))
        state = TrainState.create(params, tx)
        step = jax.jit(make_train_step(model, tx))
        losses = []
        it = ds.batches(8, seed=0)
        for _ in range(30):
            state, metrics = step(state, next(it))
            losses.append(float(metrics["loss"]))
        assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1, losses

class TestStructuredShards:
    def test_grammar_is_deterministic_and_low_entropy(self, tmp_path,
                                                      tokenizer):
        """prepare_data --structured (VERDICT r4 next #4): codes are a
        deterministic function of the caption with a small per-image
        alphabet, so training can drive loss far below the uniform
        floor; the shards flow through the production CodesDataset."""
        from dalle_tpu.cli.prepare_data import (make_motif_bank,
                                                structured_codes,
                                                synthetic_shards)

        cfg = tiny_model_config()
        bank = make_motif_bank(cfg.vocab_image)
        c1 = structured_codes("red cat boat", cfg, bank)
        c2 = structured_codes("red cat boat", cfg, bank)
        c3 = structured_codes("blue dog tree", cfg, bank)
        np.testing.assert_array_equal(c1, c2)     # deterministic
        assert not np.array_equal(c1, c3)         # caption-dependent
        assert len(np.unique(c1)) <= 64           # motif alphabet
        assert c1.shape == (cfg.image_seq_len,)
        assert (c1 >= 0).all() and (c1 < cfg.vocab_image).all()

        class Args:
            out = str(tmp_path / "structured")
            shards = 2
            records = 32
            preset = "tiny"
            seed = 0
            structured = True

        synthetic_shards(Args)
        ds = CodesDataset(str(tmp_path / "structured"), cfg,
                          tokenizer=tokenizer, shuffle_buffer=8)
        batch = next(ds.batches(4, seed=0))
        assert batch["image"].shape == (4, cfg.image_seq_len)
        # each decoded image keeps the structured alphabet
        for row in batch["image"]:
            assert len(np.unique(row)) <= 64


class TestRemoteShards:
    """URL-backed shard reading with a local cache (VERDICT r2 next #7;
    reference streams from the hub, data.py:34-38)."""

    def test_manifest_url_streams_through_cache(self, tmp_path, tokenizer,
                                                monkeypatch):
        from dalle_tpu.data import remote

        cfg = tiny_model_config()
        _make_shards(tmp_path, cfg, n_shards=2, per_shard=8)
        manifest = tmp_path / "index.txt"
        manifest.write_text("# shard list\nshard_0.msgpack\n"
                            "shard_1.msgpack\n")
        cache = tmp_path / "cache"
        monkeypatch.setattr(remote, "DEFAULT_CACHE", str(cache))
        ds = CodesDataset(f"file://{manifest}", cfg,
                          tokenizer=tokenizer, shuffle_buffer=4)
        batches = list(ds.batches(4, seed=0, loop=False))
        assert batches, "no batches from remote manifest"
        # the shards were fetched into the cache exactly once
        cached = list(cache.glob("*shard_*.msgpack"))
        assert len(cached) == 2, cached
        # a second pass rereads the cache (no new files)
        list(ds.batches(4, seed=1, loop=False))
        assert len(list(cache.glob("*shard_*.msgpack"))) == 2

    def test_single_shard_url(self, tmp_path, tokenizer):
        from dalle_tpu.data import remote

        cfg = tiny_model_config()
        _make_shards(tmp_path, cfg, n_shards=1, per_shard=8)
        cache = tmp_path / "cache2"
        openers = remote.resolve_shards(
            f"file://{tmp_path}/shard_0.msgpack", cache_dir=str(cache))
        assert len(openers) == 1
        local = openers[0]()
        assert local.startswith(str(cache))
        ds = CodesDataset(local, cfg, tokenizer=tokenizer, shuffle_buffer=4)
        assert list(ds.batches(4, seed=0, loop=False))


class TestRemoteSink:
    def test_dir_sink_uploads_atomically(self, tmp_path):
        from dalle_tpu.training.remote_sink import RemoteSink

        src = tmp_path / "ckpt_00000004.msgpack"
        src.write_bytes(b"state-bytes")
        dest = tmp_path / "mock-remote"
        sink = RemoteSink.create(f"file://{dest}")
        assert sink.upload(str(src))
        assert (dest / "ckpt_00000004.msgpack").read_bytes() == b"state-bytes"
        # overwrite-on-newer works (the aux re-archives each cadence)
        src.write_bytes(b"newer")
        assert sink.upload(str(src))
        assert (dest / "ckpt_00000004.msgpack").read_bytes() == b"newer"

    def test_unreachable_command_sink_fails_soft(self, tmp_path):
        from dalle_tpu.training.remote_sink import _CommandSink

        src = tmp_path / "x.msgpack"
        src.write_bytes(b"y")
        # a missing transfer tool (and, via timeout, a hung one) must log
        # and return False, never raise or stall the aux loop
        sink = _CommandSink(["/nonexistent-transfer-tool"],
                            "remote:/prefix", timeout=5.0)
        assert sink.upload(str(src)) is False


class TestUploadWorker:
    def test_latest_wins_and_drains_on_close(self, tmp_path):
        import time as _time

        from dalle_tpu.training.remote_sink import RemoteSink, UploadWorker

        dest = tmp_path / "remote"
        sink = RemoteSink.create(str(dest))
        slow = []

        class SlowSink:
            def upload(self, path):
                _time.sleep(0.2)
                slow.append(path)
                return sink.upload(path)

        w = UploadWorker(SlowSink(), str(dest))
        for i in range(5):  # rapid submits: intermediates are superseded
            p = tmp_path / f"ckpt_{i}.msgpack"
            p.write_bytes(b"v%d" % i)
            w.submit(str(p))
        w.close()
        # the deterministic guarantee: the FRESHEST checkpoint lands
        # (intermediates may be superseded, but a loaded box can drain
        # any number of them — no tight count bound)
        assert (dest / "ckpt_4.msgpack").read_bytes() == b"v4"
        assert len(slow) <= 5, slow
