"""Fused Pallas GEGLU FF kernel (ops/pallas/geglu_kernels.py): numerics
against the unfused lowering, model-level fused-vs-unfused parity, and the
residual-shrink property the fusion exists for (PERF.md r3 headroom #1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.ops.pallas.geglu_kernels import (geglu_ff, geglu_supported)


def _ref(x, wi, wg, wo, bi, bg, bo):
    return ((jnp.dot(x, wi) + bi)
            * jax.nn.gelu(jnp.dot(x, wg) + bg)) @ wo + bo


def _operands(key, m=256, d=128, k=512, dtype=jnp.float32):
    ks = jax.random.split(key, 7)
    return (jax.random.normal(ks[0], (m, d), dtype) * 0.5,
            jax.random.normal(ks[1], (d, k), dtype) * 0.05,
            jax.random.normal(ks[2], (d, k), dtype) * 0.05,
            jax.random.normal(ks[3], (k, d), dtype) * 0.05,
            jax.random.normal(ks[4], (k,), dtype) * 0.1,
            jax.random.normal(ks[5], (k,), dtype) * 0.1,
            jax.random.normal(ks[6], (d,), dtype) * 0.1)


class TestKernelNumerics:
    def test_forward_matches_unfused(self):
        ops = _operands(jax.random.PRNGKey(0))
        out = geglu_ff(*ops, 128, 256, True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_ref(*ops)),
                                   rtol=1e-5, atol=1e-5)

    def test_backward_matches_xla_autodiff(self):
        ops = _operands(jax.random.PRNGKey(1))

        def loss(fn):
            return lambda *a: jnp.sum(jnp.sin(fn(*a)))

        g_k = jax.grad(loss(lambda *a: geglu_ff(*a, 128, 256, True)),
                       argnums=tuple(range(7)))(*ops)
        g_r = jax.grad(loss(_ref), argnums=tuple(range(7)))(*ops)
        for a, b in zip(g_k, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_uneven_tiles_and_jit(self):
        # m=384 with block_m=256 -> picked block divides (128); k=640
        ops = _operands(jax.random.PRNGKey(2), m=384, k=640)
        fn = jax.jit(lambda *a: geglu_ff(*a, 256, 512, True))
        np.testing.assert_allclose(np.asarray(fn(*ops)),
                                   np.asarray(_ref(*ops)),
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_close_to_f32_reference(self):
        ops = _operands(jax.random.PRNGKey(3))
        xb = [a.astype(jnp.bfloat16) for a in ops]
        out = geglu_ff(*xb, 128, 256, True).astype(jnp.float32)
        ref = _ref(*ops)
        scale = float(jnp.max(jnp.abs(ref)))
        assert float(jnp.max(jnp.abs(out - ref))) / scale < 2e-2

    def test_supported_gate(self):
        assert geglu_supported(5120, 1024, 4096, jnp.bfloat16)
        assert not geglu_supported(192, 64, 256, jnp.bfloat16)   # d%128
        assert not geglu_supported(64, 128, 512, jnp.bfloat16)   # m small
        assert not geglu_supported(256, 128, 512, jnp.int8)


class TestModelIntegration:
    """ff_fusion wiring: fused model == unfused model (same params), and
    the fused plain block's FF residuals shrink to the kernel inputs."""

    @staticmethod
    def _model(ff_fusion, skip):
        from dalle_tpu.config import flagship_model_config
        from dalle_tpu.models.dalle import DALLE, init_params

        cfg = flagship_model_config(
            depth=9, dim=128, heads=2, head_dim=64, text_seq_len=16,
            image_grid=4, vocab_text=64, vocab_image=32, head_chunk=0,
            remat_skip_blocks=skip, ff_fusion=ff_fusion)
        model = DALLE(cfg)
        params = init_params(model, jax.random.PRNGKey(0))
        return cfg, model, params

    def test_fused_matches_unfused_loss_and_grads(self, monkeypatch):
        from dalle_tpu.models import attention
        monkeypatch.setattr(attention, "_PALLAS_INTERPRET", True)

        cfg, model, params = self._model("none", 1)
        _, model_f, params_f = self._model("plain", 1)
        # identical param trees (DenseKernel mirrors nn.Dense)
        assert (jax.tree.structure(params)
                == jax.tree.structure(params_f))
        text = jnp.zeros((2, cfg.text_seq_len), jnp.int32)
        image = jnp.ones((2, cfg.image_seq_len), jnp.int32)

        def loss(m):
            return lambda p: m.apply(p, text, image)[0]

        l_u = float(loss(model)(params))
        l_f = float(loss(model_f)(params))
        assert abs(l_u - l_f) / abs(l_u) < 1e-3, (l_u, l_f)

        g_u = jax.grad(loss(model))(params)
        g_f = jax.grad(loss(model_f))(params)
        flat_u, _ = jax.tree_util.tree_flatten(g_u)
        flat_f, _ = jax.tree_util.tree_flatten(g_f)
        for a, b in zip(flat_u, flat_f):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=5e-2, atol=2e-3)

    def test_param_tree_matches_dense_layout(self):
        # checkpoints trained before the DenseKernel refactor must load:
        # the FF param paths are {wi,gate,wo}/kernel with Dense's shapes
        cfg, _, params = self._model("none", 0)
        tr = params["params"]["transformer"]
        ff = (tr.get("cycle") or tr)["block_0"]["ff"]
        assert set(ff) == {"wi", "gate", "wo"}
        inner = cfg.ff_mult * cfg.dim
        assert ff["wi"]["kernel"].shape == (cfg.dim, inner)
        assert ff["gate"]["kernel"].shape == (cfg.dim, inner)
        assert ff["wo"]["kernel"].shape == (inner, cfg.dim)
        # nn.Dense parity includes the default biases (dalle-pytorch
        # FeedForward uses biased nn.Linear); dropping them broke
        # checkpoint compatibility in r4 until review caught it
        assert ff["wi"]["bias"].shape == (inner,)
        assert ff["gate"]["bias"].shape == (inner,)
        assert ff["wo"]["bias"].shape == (cfg.dim,)
