"""Access-token authorization (swarm/auth.py + matchmaking integration).

Mirrors the reference's auth surface (``huggingface_auth.py:46-193``):
authority-issued tokens bound to peer identities, expiry, refresh, and the
swarm-side gate that keeps unauthorized peers out of averaging groups.
"""

import dataclasses
import time

import pytest

from dalle_tpu.cli.issue_token import main as issue_token_main
from dalle_tpu.swarm.auth import (AccessToken, ExperimentAuthority,
                                  ExperimentAuthorizer, make_authorizer,
                                  retry_with_backoff)
from dalle_tpu.swarm.dht import get_dht_time
from dalle_tpu.swarm.identity import Identity


@pytest.fixture
def authority():
    return ExperimentAuthority(Identity.generate())


@pytest.fixture
def peer():
    return Identity.generate()


def _authorizer(authority, token=None):
    return ExperimentAuthorizer(
        authority.public_key,
        token_supplier=(lambda: token) if token is not None else None)


def test_issue_and_validate(authority, peer):
    token = authority.issue("alice", peer.public_bytes, ttl=600)
    auth = _authorizer(authority, token)
    assert auth.validate_token(token, peer.public_bytes) == "alice"
    # serialization round trip
    again = AccessToken.from_bytes(token.to_bytes())
    assert auth.validate_token(again, peer.public_bytes) == "alice"


def test_rejects_expired_forged_and_rebound(authority, peer):
    auth = _authorizer(authority)
    expired = authority.issue("bob", peer.public_bytes, ttl=-10)
    assert auth.validate_token(expired, peer.public_bytes) is None

    token = authority.issue("bob", peer.public_bytes, ttl=600)
    # bound to a different peer key -> stolen token
    other = Identity.generate()
    assert auth.validate_token(token, other.public_bytes) is None
    # forged signature
    forged = dataclasses.replace(token, signature=b"\x00" * 64)
    assert auth.validate_token(forged, peer.public_bytes) is None
    # signed by a different authority
    rogue = ExperimentAuthority(Identity.generate())
    rogue_token = rogue.issue("bob", peer.public_bytes, ttl=600)
    assert auth.validate_token(rogue_token, peer.public_bytes) is None
    # garbage bytes
    assert auth.validate_token_bytes(b"junk", peer.public_bytes) is None
    assert auth.validate_token_bytes(None, peer.public_bytes) is None


def test_refresh_on_expiry(authority, peer):
    calls = []

    def supplier():
        calls.append(1)
        ttl = 1.0 if len(calls) == 1 else 3600.0
        return authority.issue("carol", peer.public_bytes, ttl=ttl)

    auth = ExperimentAuthorizer(authority.public_key,
                                token_supplier=supplier)
    first = auth.get_token()
    assert len(calls) == 1
    # first token is inside the refresh margin -> next access re-acquires
    second = auth.get_token()
    assert len(calls) == 2
    assert second.expiration_time > first.expiration_time
    # fresh token is kept
    auth.get_token()
    assert len(calls) == 2


def test_retry_with_backoff_retries_then_raises():
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise RuntimeError("transient")
        return "ok"

    t0 = time.monotonic()
    assert retry_with_backoff(flaky, max_tries=5, initial_delay=0.01,
                              factor=2.0) == "ok"
    assert len(attempts) == 3
    assert time.monotonic() - t0 < 2.0

    def dead():
        raise RuntimeError("permanent")

    with pytest.raises(RuntimeError, match="permanent"):
        retry_with_backoff(dead, max_tries=2, initial_delay=0.01)


def test_issue_token_cli(tmp_path):
    akey = tmp_path / "authority.pem"
    pkey = tmp_path / "peer.pem"
    out = tmp_path / "alice.token"
    # authority key is created on demand; --print-public-key path
    assert issue_token_main(["--authority-key", str(akey),
                             "--print-public-key"]) == 0
    # peer identity is load-only: a missing path must NOT silently mint a
    # key the real peer does not hold
    assert issue_token_main([
        "--authority-key", str(akey), "--username", "alice",
        "--peer-identity", str(pkey), "--out", str(out)]) == 2
    Identity.load_or_create(str(pkey))  # the peer creates its own identity
    assert issue_token_main([
        "--authority-key", str(akey), "--username", "alice",
        "--peer-identity", str(pkey), "--ttl", "600",
        "--out", str(out)]) == 0

    authority = ExperimentAuthority(Identity.load_or_create(str(akey)))
    peer = Identity.load_or_create(str(pkey))
    auth = make_authorizer(authority.public_key.hex(), str(out))
    assert auth.get_token().username == "alice"
    assert auth.validate_token(auth.get_token(),
                               peer.public_bytes) == "alice"


def test_matchmaking_drops_unauthorized(tmp_path):
    """Two authorized peers + one unauthorized announcer: the group is the
    two authorized ones on every member's view."""
    from dalle_tpu.swarm.dht import DHT
    from dalle_tpu.swarm.matchmaking import make_group
    from dalle_tpu.swarm.metrics import make_validators
    import threading

    authority = ExperimentAuthority(Identity.generate())

    def node():
        ident = Identity.generate()
        return DHT(host="127.0.0.1", port=0, identity=ident,
                   record_validators=make_validators(ident, "authx"))

    a, b, c = node(), node(), node()
    try:
        for n in (b, c):
            assert n.bootstrap(a.visible_address)
        auth_a = _authorizer(authority, authority.issue(
            "a", a.identity.public_bytes, ttl=600))
        auth_b = _authorizer(authority, authority.issue(
            "b", b.identity.public_bytes, ttl=600))
        # c has a token issued by a DIFFERENT authority -> unauthorized
        rogue = ExperimentAuthority(Identity.generate())
        auth_c = _authorizer(rogue, rogue.issue(
            "c", c.identity.public_bytes, ttl=600))

        results = {}

        def run(name, dht, auth):
            results[name] = make_group(
                dht, "authx", 0, weight=1.0, matchmaking_time=4.0,
                min_group_size=2, authorizer=auth)

        threads = [threading.Thread(target=run, args=args) for args in
                   (("a", a, auth_a), ("b", b, auth_b), ("c", c, auth_c))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)

        ga, gb = results["a"], results["b"]
        assert ga is not None and gb is not None
        assert [m.peer_id for m in ga.members] == \
               [m.peer_id for m in gb.members]
        ids = {m.peer_id for m in ga.members}
        assert ids == {a.peer_id, b.peer_id}
        assert c.peer_id not in ids
    finally:
        for n in (a, b, c):
            n.shutdown()


def test_confirmation_filters_unauthorized_members(authority, peer):
    """A (possibly malicious) leader cannot confirm an unauthorized id
    into an honest follower's roster: tokens ride the signed confirmation
    and each is validated individually."""
    from dalle_tpu.swarm.matchmaking import (GroupMember,
                                             _signed_confirmation,
                                             member_authorized,
                                             verify_confirmation)

    leader = Identity.generate()
    good = Identity.generate()
    bad = Identity.generate()
    tok_leader = authority.issue("l", leader.public_bytes, ttl=600)
    tok_good = authority.issue("g", good.public_bytes, ttl=600)
    auth = _authorizer(authority, tok_leader)

    def pid(ident):
        return ident.node_id.hex()

    members = [
        GroupMember(pid(leader), "x:1", 1.0, tok_leader.to_bytes()),
        GroupMember(pid(good), "x:2", 1.0, tok_good.to_bytes()),
        GroupMember(pid(bad), "x:3", 1.0, b""),               # no token
        # stolen token: good's token attached to bad's roster entry
        GroupMember(pid(bad), "x:4", 1.0, tok_good.to_bytes()),
    ]
    assert member_authorized(members[0], auth)
    assert member_authorized(members[1], auth)
    assert not member_authorized(members[2], auth)
    assert not member_authorized(members[3], auth)

    raw = _signed_confirmation(leader, "p", 3, members)
    verified = verify_confirmation(raw, "p", 3, pid(leader), auth)
    assert verified is not None
    confirmed, _keys = verified
    assert {m.peer_id for m in confirmed} == {pid(leader), pid(good)}
    # without an authorizer everything passes through
    open_roster, _ = verify_confirmation(raw, "p", 3, pid(leader))
    assert len(open_roster) == 4

