"""VQGAN decoder + CLIP reranker: the pixel half of the inference pipeline.

The reference decodes sampled codes with a taming-transformers VQGAN and
reranks with OpenAI CLIP (``inference/run_inference.py:122-138``). These
tests prove (a) the Flax decoders run and are deterministic, (b) the torch
checkpoint mappers produce exactly the parameter trees the Flax modules
expect (round-trip through a synthetic torch state dict with the real key
schema), and (c) the CLIP BPE tokenizer implements byte-level BPE correctly
against a hand-computable merges table.
"""

import gzip
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.models.clip import (CLIPModel, CLIPTokenizer, clip_scores,
                                   map_openai_state_dict, resize_for_clip,
                                   tiny_clip_config)
from dalle_tpu.models.vqgan import (VQGANDecoder, decode_codes,
                                    map_taming_state_dict,
                                    tiny_vqgan_config)

torch = pytest.importorskip("torch")


# ---------------------------------------------------------------------------
# VQGAN
# ---------------------------------------------------------------------------

def test_vqgan_decodes_codes_to_pixels():
    cfg = tiny_vqgan_config()
    model = VQGANDecoder(cfg)
    codes = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.n_embed,
                                         (2, cfg.code_grid ** 2)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), codes)
    imgs = decode_codes(params, cfg, codes)
    assert imgs.shape == (2, cfg.resolution, cfg.resolution, 3)
    assert imgs.dtype == jnp.uint8
    again = decode_codes(params, cfg, codes)
    np.testing.assert_array_equal(np.asarray(imgs), np.asarray(again))


def _fake_taming_state_dict(cfg, flax_params):
    """Build a torch state dict with taming-transformers' key schema whose
    values are the given flax params (conv kernels transposed back), so
    loading it must reproduce the flax tree exactly."""
    sd = {}
    p = flax_params["params"]

    def put_conv(torch_name, leaf):
        sd[f"{torch_name}.weight"] = torch.tensor(
            np.transpose(np.asarray(leaf["kernel"]), (3, 2, 0, 1)))
        sd[f"{torch_name}.bias"] = torch.tensor(np.asarray(leaf["bias"]))

    def put_norm(torch_name, leaf):
        sd[f"{torch_name}.weight"] = torch.tensor(np.asarray(leaf["scale"]))
        sd[f"{torch_name}.bias"] = torch.tensor(np.asarray(leaf["bias"]))

    def put_resnet(torch_prefix, blk):
        put_norm(f"{torch_prefix}.norm1", blk["norm1"])
        put_conv(f"{torch_prefix}.conv1", blk["conv1"])
        put_norm(f"{torch_prefix}.norm2", blk["norm2"])
        put_conv(f"{torch_prefix}.conv2", blk["conv2"])
        if "nin_shortcut" in blk:
            put_conv(f"{torch_prefix}.nin_shortcut", blk["nin_shortcut"])

    def put_attn(torch_prefix, blk):
        put_norm(f"{torch_prefix}.norm", blk["norm"])
        for nm in ("q", "k", "v", "proj_out"):
            put_conv(f"{torch_prefix}.{nm}", blk[nm])

    sd["quantize.embed.weight"] = torch.tensor(np.asarray(p["codebook"]))
    put_conv("post_quant_conv", p["post_quant_conv"])
    put_conv("decoder.conv_in", p["conv_in"])
    put_resnet("decoder.mid.block_1", p["mid_block_1"])
    put_attn("decoder.mid.attn_1", p["mid_attn_1"])
    put_resnet("decoder.mid.block_2", p["mid_block_2"])
    for i_level in range(len(cfg.ch_mult)):
        for i_block in range(cfg.num_res_blocks + 1):
            key = f"up_{i_level}_block_{i_block}"
            if key in p:
                put_resnet(f"decoder.up.{i_level}.block.{i_block}", p[key])
            akey = f"up_{i_level}_attn_{i_block}"
            if akey in p:
                put_attn(f"decoder.up.{i_level}.attn.{i_block}", p[akey])
        ukey = f"up_{i_level}_upsample"
        if ukey in p:
            put_conv(f"decoder.up.{i_level}.upsample.conv", p[ukey])
    put_norm("decoder.norm_out", p["norm_out"])
    put_conv("decoder.conv_out", p["conv_out"])
    return sd


def test_taming_checkpoint_mapping_roundtrip():
    cfg = tiny_vqgan_config()
    model = VQGANDecoder(cfg)
    codes = jnp.zeros((1, cfg.code_grid ** 2), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), codes)
    sd = _fake_taming_state_dict(cfg, params)
    mapped = map_taming_state_dict(sd, cfg)

    flat_ref = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_map = jax.tree_util.tree_flatten_with_path(
        jax.tree.map(jnp.asarray, mapped))[0]
    assert [k for k, _ in flat_map] == [k for k, _ in flat_ref]
    for (path, a), (_, b) in zip(flat_map, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6,
                                   err_msg=str(path))
    # and the mapped params actually run
    imgs = decode_codes(mapped, cfg, codes)
    assert imgs.shape == (1, cfg.resolution, cfg.resolution, 3)


# ---------------------------------------------------------------------------
# CLIP
# ---------------------------------------------------------------------------

def test_clip_scores_shapes_and_selfconsistency():
    cfg = tiny_clip_config()
    model = CLIPModel(cfg)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.rand(3, cfg.image_size, cfg.image_size, 3),
                         jnp.float32)
    tokens = jnp.asarray(rng.randint(1, cfg.vocab_size, (2, cfg.context_length)),
                         jnp.int32)
    params = model.init(jax.random.PRNGKey(0), images, tokens)
    scores = clip_scores(params, cfg, images, tokens)
    assert scores.shape == (3, 2)
    assert np.all(np.abs(np.asarray(scores)) <= 1.0 + 1e-5)  # cosine range
    # identical images must tie
    images2 = jnp.concatenate([images[:1], images[:1]], axis=0)
    s2 = np.asarray(clip_scores(params, cfg, images2, tokens))
    np.testing.assert_allclose(s2[0], s2[1], atol=1e-6)


def test_clip_resize_uint8():
    cfg = tiny_clip_config()
    imgs = (np.random.RandomState(0).rand(2, 8, 8, 3) * 255).astype(np.uint8)
    out = resize_for_clip(jnp.asarray(imgs), cfg)
    assert out.shape == (2, cfg.image_size, cfg.image_size, 3)
    assert float(out.max()) <= 1.0 and float(out.min()) >= 0.0


def _fake_openai_state_dict(cfg, flax_params):
    sd = {}
    p = flax_params["params"]
    sd["visual.conv1.weight"] = torch.tensor(np.transpose(
        np.asarray(p["patch_embed"]["kernel"]), (3, 2, 0, 1)))
    sd["visual.class_embedding"] = torch.tensor(
        np.asarray(p["class_embedding"]))
    sd["visual.positional_embedding"] = torch.tensor(
        np.asarray(p["vision_pos"]))
    sd["visual.proj"] = torch.tensor(np.asarray(p["vision_proj"]))
    sd["token_embedding.weight"] = torch.tensor(
        np.asarray(p["token_embedding"]))
    sd["positional_embedding"] = torch.tensor(np.asarray(p["text_pos"]))
    sd["text_projection"] = torch.tensor(np.asarray(p["text_proj"]))
    sd["logit_scale"] = torch.tensor(np.asarray(p["logit_scale"]))

    def put_ln(torch_name, leaf):
        sd[f"{torch_name}.weight"] = torch.tensor(np.asarray(leaf["scale"]))
        sd[f"{torch_name}.bias"] = torch.tensor(np.asarray(leaf["bias"]))

    put_ln("visual.ln_pre", p["ln_pre"])
    put_ln("visual.ln_post", p["ln_post"])
    put_ln("ln_final", p["ln_final"])

    def put_block(torch_prefix, blk, width):
        put_ln(f"{torch_prefix}.ln_1", blk["ln_1"])
        put_ln(f"{torch_prefix}.ln_2", blk["ln_2"])
        attn = blk["attn"]
        ws, bs = [], []
        for nm in ("query", "key", "value"):
            k = np.asarray(attn[nm]["kernel"]).reshape(width, width)
            ws.append(k.T)
            bs.append(np.asarray(attn[nm]["bias"]).reshape(width))
        sd[f"{torch_prefix}.attn.in_proj_weight"] = torch.tensor(
            np.concatenate(ws, axis=0))
        sd[f"{torch_prefix}.attn.in_proj_bias"] = torch.tensor(
            np.concatenate(bs, axis=0))
        out_k = np.asarray(attn["out"]["kernel"]).reshape(width, width)
        sd[f"{torch_prefix}.attn.out_proj.weight"] = torch.tensor(out_k.T)
        sd[f"{torch_prefix}.attn.out_proj.bias"] = torch.tensor(
            np.asarray(attn["out"]["bias"]))
        sd[f"{torch_prefix}.mlp.c_fc.weight"] = torch.tensor(
            np.asarray(blk["mlp_fc"]["kernel"]).T)
        sd[f"{torch_prefix}.mlp.c_fc.bias"] = torch.tensor(
            np.asarray(blk["mlp_fc"]["bias"]))
        sd[f"{torch_prefix}.mlp.c_proj.weight"] = torch.tensor(
            np.asarray(blk["mlp_proj"]["kernel"]).T)
        sd[f"{torch_prefix}.mlp.c_proj.bias"] = torch.tensor(
            np.asarray(blk["mlp_proj"]["bias"]))

    for i in range(cfg.vision_layers):
        put_block(f"visual.transformer.resblocks.{i}",
                  p[f"vision_block_{i}"], cfg.vision_width)
    for i in range(cfg.text_layers):
        put_block(f"transformer.resblocks.{i}",
                  p[f"text_block_{i}"], cfg.text_width)
    return sd


def test_openai_checkpoint_mapping_preserves_scores():
    """Round-trip: flax params -> torch state dict (openai schema) ->
    mapper -> identical CLIP scores."""
    cfg = tiny_clip_config()
    model = CLIPModel(cfg)
    rng = np.random.RandomState(1)
    images = jnp.asarray(rng.rand(2, cfg.image_size, cfg.image_size, 3),
                         jnp.float32)
    tokens = jnp.asarray(rng.randint(1, cfg.vocab_size,
                                     (2, cfg.context_length)), jnp.int32)
    params = model.init(jax.random.PRNGKey(2), images, tokens)
    sd = _fake_openai_state_dict(cfg, params)
    mapped = jax.tree.map(jnp.asarray, map_openai_state_dict(sd, cfg))
    want = np.asarray(clip_scores(params, cfg, images, tokens))
    got = np.asarray(clip_scores(mapped, cfg, images, tokens))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# CLIP BPE tokenizer
# ---------------------------------------------------------------------------

def _write_merges(tmp_path, merges):
    path = tmp_path / "merges.txt.gz"
    buf = io.StringIO()
    buf.write("#version: 0.2\n")
    for a, b in merges:
        buf.write(f"{a} {b}\n")
    with gzip.open(path, "wt", encoding="utf-8") as f:
        f.write(buf.getvalue())
    return str(path)


def test_clip_bpe_tokenizer_merges(tmp_path):
    # merge 'l'+'o' -> 'lo', then 'lo'+'w</w>' -> 'low</w>'
    path = _write_merges(tmp_path, [("l", "o"), ("lo", "w</w>")])
    tok = CLIPTokenizer(path, context_length=8)
    ids = tok.encode("low")
    sot = tok.encoder["<|startoftext|>"]
    eot = tok.encoder["<|endoftext|>"]
    assert ids[0] == sot
    assert tok.encoder["low</w>"] in ids.tolist()
    assert eot in ids.tolist()
    # an unmergeable word falls back to byte tokens with </w> on the last
    ids2 = tok.encode("ox")
    assert tok.encoder["o"] in ids2.tolist()
    assert tok.encoder["x</w>"] in ids2.tolist()
    # padding and fixed length
    assert ids.shape == (8,) and ids2.shape == (8,)


def test_clip_bpe_eot_is_argmax(tmp_path):
    """encode_text locates the EOT embedding via argmax over ids — EOT must
    be the largest id the tokenizer ever emits."""
    path = _write_merges(tmp_path, [("l", "o")])
    tok = CLIPTokenizer(path, context_length=8)
    ids = tok.encode("lo x")
    assert ids.max() == tok.encoder["<|endoftext|>"]


def test_clip_tokenizer_truncation_keeps_eot(tmp_path):
    """encode_text locates the EOT embedding via argmax over ids, so
    truncation must keep EOT as the final token."""
    path = _write_merges(tmp_path, [])
    tok = CLIPTokenizer(str(path), context_length=6)
    ids = tok.encode("a very long caption that overflows the context")
    assert ids.shape == (6,)
    assert ids[-1] == tok.encoder["<|endoftext|>"]
    assert ids.max() == tok.encoder["<|endoftext|>"]
