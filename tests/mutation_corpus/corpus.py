"""Mutation corpus: hazard injections into the REAL modules.

Each entry names a flow rule, a real source file, an exact-text anchor
in it, and the replacement that reintroduces the hazard class the rule
encodes. The harness (`build_project` / `scan_mutated`) assembles the
whole-program model over the real tree once, then re-summarizes only
the mutated file per entry — so the corpus stays a few hundred
milliseconds even though every entry is a full whole-program scan.

Anchors are load-bearing: if a refactor changes the anchored code, the
corpus FAILS with "anchor drifted" instead of silently mutating
nothing. Update the anchor together with the refactor — that is the
moment to re-confirm the rule still sees the new shape.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Mutation:
    name: str          # stable id (pytest parametrize id)
    rule: str          # the flow rule that must detect the injection
    path: str          # repo-relative file the hazard is injected into
    anchor: str        # exact text that must exist (drift is loud)
    replacement: str   # the hazardous rewrite
    why: str           # the bug class this injection simulates


MUTATIONS: List[Mutation] = [
    Mutation(
        name="engine-chunk-rebind-deleted",
        rule="use-after-donate",
        path="dalle_tpu/serving/engine.py",
        anchor="self._state = _chunk_fn(self._cfg",
        replacement="_chunk_fn(self._cfg",
        why="the r9 hot loop donates EngineState through the _chunk_fn "
            "factory every iteration; deleting the rebind makes the "
            "next iteration's dispatch read the deleted buffer (the "
            "loop wrap-around read)",
    ),
    Mutation(
        name="engine-cold-admit-rebind-deleted",
        rule="use-after-donate",
        path="dalle_tpu/serving/engine.py",
        anchor="            self._state = _admit_fn(self._cfg, "
               "len(cp))(",
        replacement="            _admit_fn(self._cfg, len(cp))(",
        why="admission partitions into a cold scatter and the prefix-"
            "cache WARM scatter, both donating EngineState in "
            "sequence; deleting the cold rebind hands the warm "
            "dispatch (a function-local read two branches later) the "
            "deleted pre-scatter buffer",
    ),
    Mutation(
        name="trainer-apply-rebind-deleted",
        rule="use-after-donate",
        path="dalle_tpu/swarm/optimizer.py",
        anchor="self.state = self.apply_step(self.state, grads_tree)",
        replacement="self.apply_step(self.state, grads_tree)",
        why="the trainer's donated apply step reaches the optimizer as "
            "a CONSTRUCTOR PARAMETER (self.apply_step = apply_step, "
            "fed from task.apply_step's jitted property) — detection "
            "requires the v2 attribute-provenance link; the very next "
            "line reads self.state.params through the corpse",
    ),
    Mutation(
        name="decode-sampler-split-deleted",
        rule="rng-key-reuse",
        path="dalle_tpu/models/decode.py",
        anchor="            rng, sub = jax.random.split(rng)\n"
               "            sampled = sample_logits(sub, logits, "
               "sampling)",
        replacement="            probe = jax.random.categorical(rng, "
                    "logits)\n"
                    "            sampled = sample_logits(rng, logits, "
                    "sampling)",
        why="the decode sampler threads its key through the lax.scan "
            "carry tuple; deleting the split and drawing twice from "
            "the carry key correlates every sampled code — detection "
            "requires the v2 carry-unpack key tracking",
    ),
    Mutation(
        name="engine-metrics-lock-inversion",
        rule="lock-order-cycle",
        path="dalle_tpu/serving/engine.py",
        anchor="    def start(self) -> \"DecodeEngine\":",
        replacement="    def _probe_metrics_depth(self) -> int:\n"
                    "        with self.metrics._lock:\n"
                    "            with self._cv:\n"
                    "                return len(self._handles)\n"
                    "\n"
                    "    def start(self) -> \"DecodeEngine\":",
        why="the engine's real edge is _cv -> ServingMetrics._lock "
            "(submit under _cv records into the metrics ledger, lifted "
            "through the call graph); a method acquiring "
            "metrics._lock -> _cv closes the cycle — detection "
            "requires the v2 attribute-path lock identity "
            "(self.metrics._lock dereferenced through attr_types)",
    ),
    Mutation(
        name="engine-stale-state-stash",
        rule="donated-escape",
        path="dalle_tpu/serving/engine.py",
        anchor="            if self._tracer is None:\n"
               "                self._state = _chunk_fn(self._cfg, "
               "self._chunk, visible)(\n"
               "                    self._params, self._state)",
        replacement="            self._prev_state = self._state\n"
                    "            if self._tracer is None:\n"
                    "                self._state = _chunk_fn(self._cfg, "
                    "self._chunk, visible)(\n"
                    "                    self._params, self._state)\n"
                    "                _stale = self._prev_state.pos",
        why="stashing the pre-chunk state in an attribute and reading "
            "it after the donating dispatch is the exact shape a "
            "unified device-state substrate (ROADMAP direction 5) "
            "could reintroduce: the holder references the deleted "
            "buffer",
    ),
    # -- race family (Eraser-style lockset + thread roles) ----------------
    Mutation(
        name="engine-start-field-init-moved",
        rule="shared-write-unlocked",
        path="dalle_tpu/serving/engine.py",
        anchor="    def start(self) -> \"DecodeEngine\":\n"
               "        self._thread.start()\n"
               "        return self",
        replacement="    def start(self) -> \"DecodeEngine\":\n"
                    "        self._thread.start()\n"
                    "        self._pos_host = np.full(\n"
                    "            (self._serving.n_slots,),\n"
                    "            self._cfg.total_seq_len, np.int32)\n"
                    "        return self",
        why="moving a field init AFTER the Thread.start() publication "
            "point races the engine loop's very first chunk against "
            "the re-initialization — the init-before-start "
            "happens-before seed no longer covers the write, and "
            "_pos_host becomes visible to two roles with no lock",
    ),
    Mutation(
        name="engine-take-cancels-lock-dropped",
        rule="shared-write-unlocked",
        path="dalle_tpu/serving/engine.py",
        anchor="    def _take_cancels(self) -> Dict[int, str]:\n"
               "        with self._cv:\n"
               "            cancels, self._cancel_rids = "
               "self._cancel_rids, {}\n"
               "        return cancels",
        replacement="    def _take_cancels(self) -> Dict[int, str]:\n"
                    "        cancels, self._cancel_rids = "
                    "self._cancel_rids, {}\n"
                    "        return cancels",
        why="the r12 cancel-vs-complete ledger: cancel() appends rids "
            "under _cv from the front-end while the engine thread "
            "swaps the dict at the boundary — dropping the lock makes "
            "the swap lose a concurrent cancellation (the request "
            "decodes to completion against an owner who already gave "
            "up)",
    ),
    Mutation(
        name="engine-take-cancels-wrong-lock",
        rule="lock-inconsistent-access",
        path="dalle_tpu/serving/engine.py",
        anchor="    def _take_cancels(self) -> Dict[int, str]:\n"
               "        with self._cv:\n"
               "            cancels, self._cancel_rids = "
               "self._cancel_rids, {}\n"
               "        return cancels",
        replacement="    def _take_cancels(self) -> Dict[int, str]:\n"
                    "        with self.metrics._lock:\n"
                    "            cancels, self._cancel_rids = "
                    "self._cancel_rids, {}\n"
                    "        return cancels",
        why="holding A lock is not holding THE lock: every other "
            "_cancel_rids access synchronizes on _cv, so a swap under "
            "metrics._lock synchronizes nothing — the lockset "
            "intersection across accesses must come up empty even "
            "though no single access is bare",
    ),
    Mutation(
        name="router-table-refresh-lock-dropped",
        rule="shared-write-unlocked",
        path="dalle_tpu/serving/router.py",
        anchor="        with self._lock:\n"
               "            self._table = fresh",
        replacement="        self._table = fresh",
        why="the refresher thread republishes the placement table "
            "every period while request threads read it for placement "
            "— dropping the lock tears the swap against a concurrent "
            "snapshot (the r18 router's one load-bearing cross-thread "
            "handoff)",
    ),
    Mutation(
        name="health-remote-strike-lock-dropped",
        rule="shared-write-unlocked",
        path="dalle_tpu/swarm/health.py",
        anchor="        w = weight or STRIKE_WEIGHTS.get(reason, 1.0)\n"
               "        with self._lock:",
        replacement="        w = weight or "
                    "STRIKE_WEIGHTS.get(reason, 1.0)\n"
                    "        if True:",
        why="StrikeGossip.run folds verified receipts into the ledger "
            "through remote_strike (resolved through the "
            "PeerHealthLedger ctor annotation) while the training "
            "thread reads scores under _lock — dropping the fold's "
            "lock loses concurrent strikes from the reputation ledger",
    ),
    Mutation(
        name="engine-readiness-pos-mirror-read",
        rule="shared-write-unlocked",
        path="dalle_tpu/serving/engine.py",
        anchor="        out[\"live_slots\"] = sum(p is not None "
               "for p in self._slots)",
        replacement="        out[\"live_slots\"] = sum(p is not None "
                    "for p in self._slots)\n"
                    "        out[\"decode_pos_min\"] = "
                    "int(self._pos_host.min())",
        why="reading the engine-thread-owned position mirror from the "
            "probe role drags _pos_host into two roles: unlike _slots "
            "(annotated handoff: fixed-length list of refs), a numpy "
            "reduction over a vector the loop mutates in place can "
            "tear mid-scan — the detector must flag the loop's "
            "unlocked writes once a second role reads the mirror",
    ),
    Mutation(
        name="allreduce-inflight-table-lock-dropped",
        rule="shared-write-unlocked",
        path="dalle_tpu/swarm/allreduce.py",
        anchor="        done_part = False\n"
               "        with self._cv:\n"
               "            pend_set = self._parts.get(part)\n"
               "            if pend_set is None or ci not in pend_set:\n"
               "                return False  # duplicate chunk or "
               "completed part\n"
               "            pend_set.discard(ci)\n"
               "            self._progressed = True\n"
               "            if not pend_set:\n"
               "                self._parts.pop(part, None)\n"
               "                done_part = True\n"
               "                self._cv.notify_all()",
        replacement="        done_part = False\n"
                    "        pend_set = self._parts.get(part)\n"
                    "        if pend_set is None or ci not in pend_set:\n"
                    "            return False  # duplicate chunk or "
                    "completed part\n"
                    "        pend_set.discard(ci)\n"
                    "        self._progressed = True\n"
                    "        if not pend_set:\n"
                    "            self._parts.pop(part, None)\n"
                    "            done_part = True",
        why="the r19 pipelined gather's per-part in-flight table: the "
            "drain thread completes chunks and pops finished parts "
            "while the round thread snapshots the leftovers in "
            "finish() under the same _cv — dropping the drain-side "
            "lock races the pop against the snapshot (a part could be "
            "both 'gathered' and 'timed out' in the same round)",
    ),
    Mutation(
        name="allreduce-completion-flag-bare-read",
        rule="lock-inconsistent-access",
        path="dalle_tpu/swarm/allreduce.py",
        anchor="        with self._cv:\n"
               "            while not (self._complete or self._dead):\n"
               "                self._cv.wait(timeout=0.5)\n"
               "            leftover = {k: set(v) for k, v in "
               "self._parts.items()}\n"
               "            bans = list(self._bans)\n"
               "            progressed = self._progressed\n"
               "        self._thread.join()",
        replacement="        while not (self._complete or self._dead):\n"
                    "            time.sleep(0.05)\n"
                    "        with self._cv:\n"
                    "            leftover = {k: set(v) for k, v in "
                    "self._parts.items()}\n"
                    "            bans = list(self._bans)\n"
                    "            progressed = self._progressed\n"
                    "        self._thread.join()",
        why="turning finish()'s condition-variable wait into a bare "
            "busy-spin reads the drain's completion flags with no lock "
            "while every write to them happens under _cv — the lockset "
            "intersection across accesses comes up empty (and the read "
            "is BEFORE the join, so the post-join exemption must not "
            "swallow it)",
    ),
    Mutation(
        name="evidence-fetch-completion-lock-dropped",
        rule="shared-write-unlocked",
        path="dalle_tpu/swarm/audit.py",
        anchor="            with self._cv:\n"
               "                job[\"blob\"] = blob\n"
               "                job[\"done\"] = True\n"
               "                self._inflight.pop(digest, None)\n"
               "                if blob is not None:\n"
               "                    self.fetch_ok += 1\n"
               "                    self.fetch_bytes += len(blob)\n"
               "                    if job.get(\"failover\"):\n"
               "                        self.fetch_failover += 1\n"
               "                    self._retain_locked(digest, blob)\n"
               "                else:\n"
               "                    self.fetch_failed += 1\n"
               "                self._cv.notify_all()",
        replacement="            job[\"blob\"] = blob\n"
                    "            job[\"done\"] = True\n"
                    "            self._inflight.pop(digest, None)\n"
                    "            if blob is not None:\n"
                    "                self.fetch_ok += 1\n"
                    "                self.fetch_bytes += len(blob)\n"
                    "                if job.get(\"failover\"):\n"
                    "                    self.fetch_failover += 1\n"
                    "                self._retain_locked(digest, blob)\n"
                    "            else:\n"
                    "                self.fetch_failed += 1",
        why="the r20 evidence fetch worker lands a finished job — "
            "blob, done flag, in-flight-table pop, counters, retained-"
            "bundle insert — under _cv, while verifier threads "
            "cv-wait on the same job dict in fetch() and counters() "
            "snapshots the totals; dropping the worker-side lock "
            "races the completion against the waiter's bounded wait "
            "(a fetch could time out AND return the blob) and tears "
            "the counter snapshot",
    ),
    Mutation(
        name="evidence-plane-field-init-moved",
        rule="shared-write-unlocked",
        path="dalle_tpu/swarm/audit.py",
        anchor="        self._refresh_due = time.monotonic() "
               "+ self.serve_ttl / 4\n"
               "        self._thread = threading.Thread("
               "target=self._run, daemon=True,\n"
               "                                        "
               "name=\"evidence-fetch\")\n"
               "        self._thread.start()",
        replacement="        self._thread = threading.Thread("
                    "target=self._run, daemon=True,\n"
                    "                                        "
                    "name=\"evidence-fetch\")\n"
                    "        self._thread.start()\n"
                    "        self._refresh_due = time.monotonic() "
                    "+ self.serve_ttl / 4",
        why="the evidence plane's worker is started LAST in __init__ "
            "so every field init happens-before its first read; "
            "moving the serve-refresh deadline init after "
            "Thread.start() races the worker's idle-loop read of "
            "_refresh_due (under _cv) against an unlocked post-start "
            "write — the init-before-start seed no longer covers it",
    ),
]


# -- harness ---------------------------------------------------------------

def load_tree() -> Dict[str, str]:
    """{repo-relative path: source} for the real dalle_tpu/ tree."""
    sources: Dict[str, str] = {}
    pkg = os.path.join(REPO, "dalle_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            p = os.path.join(dirpath, name)
            rel = os.path.relpath(p, REPO).replace(os.sep, "/")
            with open(p, "r", encoding="utf-8") as fh:
                sources[rel] = fh.read()
    return sources


def summarize_tree(sources: Dict[str, str]) -> Dict[str, dict]:
    from dalle_tpu.analysis.project import summarize_source
    out = {}
    for rel, src in sources.items():
        try:
            out[rel] = summarize_source(rel, src)
        except SyntaxError:
            pass
    return out


def run_rule(rule: str, summaries: Dict[str, dict],
             sources: Dict[str, str]) -> List:
    from dalle_tpu.analysis.core import PROJECT_RULES, _load_rules
    from dalle_tpu.analysis.project import Project
    _load_rules()
    project = Project(summaries, sources)
    return [f for f in PROJECT_RULES[rule].fn(project) if f is not None]


def scan_mutated(mut: Mutation, sources: Dict[str, str],
                 summaries: Dict[str, dict]
                 ) -> Tuple[Optional[str], List]:
    """Apply one mutation and run its rule over the re-assembled
    project. Returns (error, findings): error is set when the anchor
    drifted (the corpus must fail loudly, not skip)."""
    from dalle_tpu.analysis.project import summarize_source
    src = sources.get(mut.path)
    if src is None:
        return f"{mut.path} is gone — update the corpus", []
    if mut.anchor not in src:
        return (f"anchor drifted in {mut.path} — the real code changed; "
                f"update mutation '{mut.name}' alongside it", [])
    mutated = dict(sources)
    mutated[mut.path] = src.replace(mut.anchor, mut.replacement)
    try:
        mut_summary = summarize_source(mut.path, mutated[mut.path])
    except SyntaxError as e:
        return f"mutation '{mut.name}' does not parse: {e}", []
    mut_summaries = dict(summaries)
    mut_summaries[mut.path] = mut_summary
    findings = run_rule(mut.rule, mut_summaries, mutated)
    return None, [f for f in findings if f.path == mut.path]
