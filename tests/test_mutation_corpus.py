"""Tier-1 gate over the graftlint mutation corpus
(tests/mutation_corpus/): every known hazard class, injected into the
REAL engine/trainer/model modules, must be detected by its flow rule.

This is the enforced half of the flow-rule contract (LINTS.md "The
mutation-corpus contract"): the per-rule fixtures prove a rule CAN
fire; this proves the whole-program approximation still SEES the real
call sites the rule exists for — the half that rots silently when a
refactor changes a shape the resolver no longer recognizes. An
undetected injection fails tier-1; a drifted anchor fails tier-1 too
(loudly, instead of mutating nothing).

The project model over the unmutated tree is summarized once per
session; each entry re-summarizes only its mutated file, so the whole
corpus is one cold-parse plus milliseconds per mutation.
"""

import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "graftlint_mutation_corpus",
    os.path.join(REPO, "tests", "mutation_corpus", "corpus.py"))
corpus = importlib.util.module_from_spec(_spec)
sys.modules[_spec.name] = corpus   # dataclasses resolves __module__
_spec.loader.exec_module(corpus)


@pytest.fixture(scope="module")
def tree():
    sources = corpus.load_tree()
    summaries = corpus.summarize_tree(sources)
    return sources, summaries


@pytest.fixture(scope="module")
def clean_by_rule(tree):
    """Precondition per rule: the UNMUTATED tree is clean, so any
    finding after an injection is attributable to the injection."""
    sources, summaries = tree
    out = {}
    for rule in sorted({m.rule for m in corpus.MUTATIONS}):
        out[rule] = corpus.run_rule(rule, summaries, sources)
    return out


def test_corpus_covers_every_flow_rule():
    """The contract floor: >= 1 injection per registered flow rule —
    a new flow rule ships with its mutation or fails here."""
    from dalle_tpu.analysis import PROJECT_RULES
    covered = {m.rule for m in corpus.MUTATIONS}
    assert covered == set(PROJECT_RULES), (
        f"flow rules without a real-module mutation: "
        f"{set(PROJECT_RULES) - covered}")


def test_real_tree_is_clean_for_corpus_rules(clean_by_rule):
    for rule, findings in clean_by_rule.items():
        assert findings == [], (
            f"{rule} fires on the UNMUTATED tree — fix the finding "
            f"first, the corpus needs a clean baseline: "
            f"{[f.format() for f in findings]}")


@pytest.mark.parametrize("mut", corpus.MUTATIONS,
                         ids=[m.name for m in corpus.MUTATIONS])
def test_injected_hazard_is_detected(mut, tree, clean_by_rule):
    sources, summaries = tree
    error, findings = corpus.scan_mutated(mut, sources, summaries)
    assert error is None, error
    assert findings, (
        f"rule '{mut.rule}' went blind on mutation '{mut.name}' "
        f"({mut.path}): {mut.why}")
    assert all(f.rule == mut.rule for f in findings)
