"""Data-plane confidentiality (swarm/crypto.py + group-key distribution).

The reference gets transport encryption from libp2p's security handshake
(SURVEY.md §2 component 17); here it is framing-level: X25519 sealed boxes
for state streams and per-round group keys (sealed into the signed
matchmaking confirmation) for all-reduce chunks. VERDICT r1 weak #7.
"""

import threading

import numpy as np
import pytest

from dalle_tpu.swarm.crypto import (KxKeypair, decrypt, encrypt,
                                    new_group_key, open_sealed, seal_to)
from dalle_tpu.swarm.dht import DHT
from dalle_tpu.swarm.identity import Identity
from dalle_tpu.swarm.matchmaking import make_group


def test_sealed_box_roundtrip_and_tamper():
    kx = KxKeypair()
    blob = seal_to(kx.public_bytes, b"secret payload")
    assert open_sealed(kx, blob) == b"secret payload"
    # sealed blobs are never plaintext
    assert b"secret payload" not in blob
    # tampering anywhere breaks the AEAD
    for i in (0, 16, 40, len(blob) - 1):
        bad = bytearray(blob)
        bad[i] ^= 1
        assert open_sealed(kx, bytes(bad)) is None
    # a different recipient cannot open
    assert open_sealed(KxKeypair(), blob) is None
    assert open_sealed(kx, b"short") is None


def test_group_key_aead():
    key = new_group_key()
    ct = encrypt(key, b"gradient bytes")
    assert decrypt(key, ct) == b"gradient bytes"
    assert b"gradient bytes" not in ct
    assert decrypt(new_group_key(), ct) is None
    bad = bytearray(ct)
    bad[-1] ^= 1
    assert decrypt(key, bytes(bad)) is None
    # nonces are fresh per message
    assert encrypt(key, b"x") != encrypt(key, b"x")


def _node():
    return DHT(host="127.0.0.1", port=0, identity=Identity.generate())


def test_matchmaking_distributes_group_key():
    a, b = _node(), _node()
    try:
        assert b.bootstrap(a.visible_address)
        results = {}

        def run(name, dht):
            results[name] = make_group(dht, "gk", 0, weight=1.0,
                                       matchmaking_time=4.0,
                                       min_group_size=2, encrypt=True)

        threads = [threading.Thread(target=run, args=(n, d))
                   for n, d in (("a", a), ("b", b))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)

        ga, gb = results["a"], results["b"]
        assert ga is not None and gb is not None
        assert ga.size == gb.size == 2
        assert ga.group_key is not None and len(ga.group_key) == 32
        assert ga.group_key == gb.group_key  # both hold the round key
        # the key in the wire confirmation was sealed, not plaintext
        # (the AEAD property above plus: encrypt=False rounds carry none)
    finally:
        a.shutdown()
        b.shutdown()


def test_matchmaking_without_encrypt_has_no_key():
    a = _node()
    try:
        g = make_group(a, "nk", 0, weight=1.0, matchmaking_time=0.5,
                       min_group_size=1, encrypt=True)
        # solo group: nothing to encrypt, no key minted
        assert g is not None and g.group_key is None
        g2 = make_group(a, "nk2", 0, weight=1.0, matchmaking_time=0.5,
                        min_group_size=1, encrypt=False)
        assert g2 is not None and g2.group_key is None
    finally:
        a.shutdown()


def test_encrypted_allreduce_and_eavesdropper():
    """Two peers average under a group key; a third peer that knows the
    run id and tags but lacks the key reads only ciphertext."""
    from dalle_tpu.swarm.allreduce import run_allreduce

    a, b = _node(), _node()
    try:
        assert b.bootstrap(a.visible_address)
        groups = {}

        def mm(name, dht):
            groups[name] = make_group(dht, "ear", 0, weight=1.0,
                                      matchmaking_time=4.0,
                                      min_group_size=2, encrypt=True)

        ts = [threading.Thread(target=mm, args=(n, d))
              for n, d in (("a", a), ("b", b))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        ga, gb = groups["a"], groups["b"]
        assert ga.group_key == gb.group_key is not None

        data = {"a": [np.full((1000,), 2.0, np.float32)],
                "b": [np.full((1000,), 4.0, np.float32)]}
        out = {}

        def ar(name, dht, group):
            out[name] = run_allreduce(dht, group, "ear", 0, data[name],
                                      weight=1.0, allreduce_timeout=15.0)

        ts = [threading.Thread(target=ar, args=("a", a, ga)),
              threading.Thread(target=ar, args=("b", b, gb))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        np.testing.assert_allclose(out["a"][0], 3.0, atol=1e-2)
        np.testing.assert_array_equal(out["a"][0], out["b"][0])

        # an eavesdropper's mailbox fetch of an encrypted chunk (if any
        # were posted) would be AEAD bytes; simulate at the primitive
        # level: frames under the group key are not parseable without it
        from dalle_tpu.swarm.crypto import maybe_encrypt
        frame = maybe_encrypt(ga.group_key, b"\x00" * 64)
        assert decrypt(new_group_key(), frame) is None
    finally:
        a.shutdown()
        b.shutdown()


def test_state_transfer_is_sealed():
    """The state stream decodes only for the requester: a stream served to
    kx key A is unreadable with kx key B (the chunks are sealed boxes)."""
    from dalle_tpu.swarm.state_transfer import (StateServer,
                                                load_state_from_peers)
    import time

    a, b = _node(), _node()
    try:
        assert b.bootstrap(a.visible_address)
        arrays = [np.arange(32, dtype=np.float32)]
        server = StateServer(a, "enc", lambda: (3, arrays),
                             announce_period=0.2)
        server.start()
        try:
            deadline = time.monotonic() + 10
            result = None
            while result is None and time.monotonic() < deadline:
                result = load_state_from_peers(b, "enc", timeout=3.0)
            assert result is not None
            epoch, got = result
            assert epoch == 3
            np.testing.assert_allclose(got[0], arrays[0], atol=1e-3)

            # direct proof the wire chunks are sealed: serve a chunk to a
            # known kx key and check another key cannot open it
            from dalle_tpu.swarm.state_transfer import _seal_maybe
            kx = KxKeypair()
            frame = _seal_maybe(kx.public_bytes, b"signed-frame-bytes")
            assert open_sealed(KxKeypair(), frame) is None
            assert open_sealed(kx, frame) == b"signed-frame-bytes"
        finally:
            server.stop()
    finally:
        a.shutdown()
        b.shutdown()
