"""graftlint (dalle_tpu/analysis): per-rule positive/negative fixtures,
suppression + baseline mechanics, and the tier-1 enforcement scan of the
real codebase against lint_baseline.json.

The fixtures are the rules' regression harness: every rule must catch
its violating snippet AND stay quiet on the idiomatic equivalent, so a
refactor of the analyzer cannot silently lobotomize a rule. The repo
scan is the enforcement face: any new unbaselined finding fails tier-1.

Everything here is stdlib-ast work over in-memory strings plus one parse
pass of ~70 files — no subprocesses, no jax tracing — so the whole
module runs in low single-digit seconds on the 2-core CI box.
"""

import os
import time

import pytest

from dalle_tpu.analysis import (PROJECT_RULES, RULES, analyze_paths,
                                analyze_source, analyze_sources,
                                diff_baseline, fingerprint_findings,
                                load_baseline, save_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (rule, fixture path, violating source, idiomatic source). The path
# matters for module-role rules: device-module fixtures pretend to live
# under dalle_tpu/ops/, quant fixtures in a quant module.
FIXTURES = [
    (
        "host-sync-in-jit",
        "dalle_tpu/fake.py",
        """
import jax
@jax.jit
def f(x):
    return float(x) + x.item()
""",
        """
import jax
import jax.numpy as jnp
@jax.jit
def f(x):
    return x.astype(jnp.float32) + jnp.sum(x)
def host_helper(x):
    return float(x)  # not traced: fine
""",
    ),
    (
        "host-sync-in-jit",
        "dalle_tpu/fake_pallas.py",
        """
from jax.experimental import pallas as pl
def _kern(x_ref, o_ref):
    o_ref[:] = x_ref[:].tolist()
def call(x):
    return pl.pallas_call(_kern, out_shape=None)(x)
""",
        """
from jax.experimental import pallas as pl
def _kern(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0
def call(x):
    return pl.pallas_call(_kern, out_shape=None)(x)
""",
    ),
    (
        "python-rng-in-device",
        "dalle_tpu/ops/fake.py",
        """
import numpy as np
def init_mask(shape):
    return np.random.rand(*shape) > 0.5
""",
        """
import numpy as np
import jax
def init_mask(key, seed, shape):
    rng = np.random.default_rng(seed)      # seeded: reproducible
    jmask = jax.random.bernoulli(key, 0.5, shape)
    return rng, jmask
""",
    ),
    (
        "python-rng-in-device",
        "dalle_tpu/fake.py",
        """
import jax, random
@jax.jit
def f(x):
    return x * random.random()
""",
        """
import jax
@jax.jit
def f(key, x):
    return x * jax.random.uniform(key)
""",
    ),
    (
        "nondet-pytree",
        "dalle_tpu/fake.py",
        """
import jax, time
@jax.jit
def f(x):
    return x + time.time()
""",
        """
import jax
@jax.jit
def f(x, now):
    return x + now          # wall clock rides in as an operand
""",
    ),
    (
        "nondet-pytree",
        "dalle_tpu/fake.py",
        """
import jax
@jax.jit
def f(tree):
    return [tree[k] for k in {"w", "b"}]
""",
        """
import jax
@jax.jit
def f(tree):
    return [tree[k] for k in sorted(tree)]   # deterministic order
""",
    ),
    (
        "literal-divisor-in-quant",
        "dalle_tpu/ops/pallas/fake_quant.py",
        """
import jax.numpy as jnp
def encode(absmax):
    scales = absmax / 127.0
    return scales
""",
        """
import jax.numpy as jnp
def encode(absmax, d127):
    scales = absmax / d127   # divisor rides as a runtime operand
    return scales
""",
    ),
    (
        "host-sync-in-hot-loop",
        "dalle_tpu/serving/fake.py",
        """
import numpy as np
import jax
def serve_loop(state, chunk_fn, total):
    while True:
        state = chunk_fn(state)
        pos = np.asarray(state.pos)          # blocking pull per chunk
        done = int(pos[0]) >= total
        flags = jax.device_get(state.flags)
        depth = state.depth.item()
        if done:
            break
""",
        """
import numpy as np
def _harvest(state, slot):
    return np.asarray(state.codes[slot])     # per-completion, no loop
def serve_loop(state, chunk_fn, pos_host, chunk, total):
    rows = []
    while True:
        state = chunk_fn(state)
        pos_host[:] = np.minimum(pos_host + chunk, total)  # host mirror
        if pos_host[0] >= total:
            rows.append(_harvest(state, 0))
            break
    n = int(np.asarray(rows).sum())          # outside the loop: fine
    return rows, n
""",
    ),
    (
        "silent-except",
        "dalle_tpu/swarm/fake.py",
        """
def recv_round(sock):
    try:
        return sock.recv()
    except Exception:
        return None
""",
        """
import logging
logger = logging.getLogger(__name__)
def recv_round(sock):
    try:
        return sock.recv()
    except Exception:
        logger.warning("round recv failed", exc_info=True)
        return None
def parse_port(s):
    try:
        return int(s)
    except ValueError:       # narrow except: deliberate, passes
        return None
""",
    ),
    (
        "blocking-in-async",
        "dalle_tpu/fake.py",
        """
import time
async def pump(queue):
    time.sleep(0.5)
    return await queue.get()
""",
        """
import asyncio, time
async def pump(queue, executor):
    def _worker():           # runs on the executor, not the loop:
        time.sleep(0.5)      # nested sync defs are NOT the coroutine
    await asyncio.get_event_loop().run_in_executor(executor, _worker)
    await asyncio.sleep(0.5)
    return await queue.get()
""",
    ),
    (
        "thread-daemon-join",
        "dalle_tpu/fake.py",
        """
import threading
def spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t
""",
        """
import threading
class Owner:
    def __init__(self, fn):
        self._thread = threading.Thread(target=fn, daemon=True)
    def start(self):
        self._thread.start()
    def stop(self):
        self._thread.join(timeout=5.0)
def spawn_joined(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()
    return t
""",
    ),
    (
        "thread-daemon-join",
        "dalle_tpu/fake_subclass.py",
        """
import threading
class Worker(threading.Thread):
    def __init__(self, fn):
        super().__init__()
        self.fn = fn
""",
        """
import threading
class Worker(threading.Thread):
    def __init__(self, fn):
        super().__init__(daemon=True, name="worker")
        self.fn = fn
""",
    ),
    (
        "unchecked-pool-future",
        "dalle_tpu/swarm/fake.py",
        """
import concurrent.futures
def scatter(work, items):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        pool.submit(work, items[0])                 # fire-and-forget
        futs = [pool.submit(work, it) for it in items]
        concurrent.futures.wait(futs)               # observes, never reads
""",
        """
import concurrent.futures
def scatter(work, items, log):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        one = pool.submit(work, items[0])
        one.add_done_callback(log)
        futs = [pool.submit(work, it) for it in items]
        done, straggling = concurrent.futures.wait(futs, timeout=5.0)
        failed = sum(1 for f in done
                     if f.exception() is not None or not f.result())
        retry_futs = [pool.submit(work, it) for it in items[:failed]]
        for f in retry_futs:
            f.result()
        handed_off = [pool.submit(work, it) for it in items]
        return handed_off                # escapes: the caller consumes
""",
    ),
    (
        "use-after-donate",
        "dalle_tpu/fake.py",
        """
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
def train(state, grads):
    _step(state, grads)              # donation without rebinding...
    return state.loss                # ...then a read through the corpse
""",
        """
import functools
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
@functools.partial(jax.jit, donate_argnums=1)
def apply2(params, state):
    return state
def train(state, grads, params):
    state = _step(state, grads)      # rebind: the sanctioned shape
    state = apply2(params, state)    # decorator-partial form, donated pos 1
    return state.loss
def fresh(state0, grads):
    _step(state0, grads)             # donated, never read again: fine
    return grads
""",
    ),
    (
        "lock-order-cycle",
        "dalle_tpu/fake.py",
        """
import threading
class Pair:
    def __init__(self):
        self._head = threading.Lock()
        self._tail = threading.Lock()
    def push(self):
        with self._head:
            with self._tail:
                return 1
    def pop(self):
        with self._tail:
            with self._head:         # inverted: deadlock with push()
                return 2
""",
        """
import threading
class Pair:
    def __init__(self):
        self._head = threading.Lock()
        self._tail = threading.Lock()
    def _locked_tail(self):
        with self._tail:
            return 1
    def push(self):
        with self._head:
            return self._locked_tail()   # head->tail, via the call graph
    def pop(self):
        with self._head:
            with self._tail:             # head->tail, directly: consistent
                return 2
""",
    ),
    (
        "rng-key-reuse",
        "dalle_tpu/fake.py",
        """
import jax
def sample(rng):
    a = jax.random.normal(rng, (4,))
    b = jax.random.uniform(rng, (4,))    # same key: correlated draws
    return a + b
""",
        """
import jax
def sample(rng):
    rng, sub = jax.random.split(rng)     # split first: both fresh
    a = jax.random.normal(sub, (4,))
    b = jax.random.uniform(rng, (4,))
    return a + b
def per_step(rng, i):
    step_rng = jax.random.fold_in(rng, i)    # sanctioned derivation
    a = jax.random.normal(step_rng, ())
    b = jax.random.uniform(jax.random.fold_in(rng, i + 1), ())
    return a + b
def exclusive(rng, traced):
    if traced:
        return jax.random.normal(rng, ())    # early exit: paths are
    return jax.random.uniform(rng, ())       # exclusive, no reuse
""",
    ),
    # r12 serving-overload shapes: the cancel/shed paths ride the same
    # rule families — pin the hazardous variants of each new shape
    (
        "unchecked-pool-future",
        "dalle_tpu/serving/fake_cancel.py",
        """
import concurrent.futures
def cancel_all(engine, rids):
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
        futs = [pool.submit(engine.cancel, r) for r in rids]
        concurrent.futures.wait(futs)   # a failed cancel vanishes
""",
        """
import concurrent.futures
def cancel_all(engine, rids):
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
        futs = [pool.submit(engine.cancel, r) for r in rids]
        return [f.result() for f in futs]   # surfaced per cancel
""",
    ),
    (
        "host-sync-in-hot-loop",
        "dalle_tpu/serving/fake_shed.py",
        """
def shed_expired(state, queue, now, service):
    while queue:
        pend = queue[0]
        pos = int(state.pos[0])            # device pull per iteration
        if pos > 0 and now + service > pend.deadline:
            queue.pop(0)
        else:
            break
""",
        """
def shed_expired(pos_host, queue, now, service):
    while queue:
        pend = queue[0]                     # host mirror + host clocks:
        if pos_host[0] > 0 and now + service > pend.deadline:
            queue.pop(0)                    # no device round-trip
        else:
            break
""",
    ),
    (
        "use-after-donate",
        "dalle_tpu/fake_release.py",
        """
import jax
def release(state, slots):
    return state
_rel = jax.jit(release, donate_argnums=0)
def cancel_slots(state, slots):
    _rel(state, slots)               # donated, never rebound...
    return state.pos                 # ...then a read through the corpse
""",
        """
import jax
def release(state, slots):
    return state
_rel = jax.jit(release, donate_argnums=0)
def cancel_slots(state, slots):
    state = _rel(state, slots)       # rebind: the sanctioned shape
    return state.pos
""",
    ),
    # Byzantine-gossip shapes (swarm/health.py StrikeGossip): the
    # worker publishes receipts from a background thread and could
    # plausibly fan folds out through a pool — pin the hazardous
    # variant of each shape so the real worker can never regress into
    # them unnoticed.
    (
        "unchecked-pool-future",
        "dalle_tpu/swarm/fake_gossip.py",
        """
import concurrent.futures
def publish_receipts(dht, receipts, key):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        futs = [pool.submit(dht.store, key, sub, body, exp)
                for sub, body, exp in receipts]
        concurrent.futures.wait(futs)   # a failed store (and its
        # receipt) vanishes without a trace
""",
        """
import concurrent.futures
def publish_receipts(dht, receipts, key):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        futs = [pool.submit(dht.store, key, sub, body, exp)
                for sub, body, exp in receipts]
        return sum(1 for f in futs if f.result())   # read every store
""",
    ),
    (
        "thread-daemon-join",
        "dalle_tpu/swarm/fake_gossip_worker.py",
        """
import threading
class Gossip(threading.Thread):
    def __init__(self, dht, ledger):
        super().__init__()           # non-daemon, and stop() below
        self.dht = dht               # never joins: interpreter exit
        self._stop = threading.Event()   # blocks on a live publish
    def stop(self):
        self._stop.set()
""",
        """
import threading
class Gossip(threading.Thread):
    def __init__(self, dht, ledger):
        super().__init__(daemon=True, name="strike-gossip")
        self.dht = dht
        self._stop = threading.Event()
    def stop(self, join_timeout=10.0):
        self._stop.set()
        if join_timeout is not None and self.is_alive() \\
                and threading.current_thread() is not self:
            self.join(timeout=join_timeout)
""",
    ),
    # Aggregation-audit shapes (swarm/audit.py): the worker fans
    # per-part replays out through a pool and runs fetches from a
    # background thread against the native DHT — pin the hazardous
    # variant of each shape so the real worker can never regress into
    # them unnoticed.
    (
        "unchecked-pool-future",
        "dalle_tpu/swarm/fake_audit.py",
        """
import concurrent.futures
def audit_parts(dht, parts, replay):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        futs = [pool.submit(replay, dht, p) for p in parts]
        concurrent.futures.wait(futs)   # a FAILED replay (the whole
        # point of the audit) vanishes in an unread Future
""",
        """
import concurrent.futures
def audit_parts(dht, parts, replay):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        futs = [pool.submit(replay, dht, p) for p in parts]
        return [f.result() for f in futs]   # every verdict surfaced
""",
    ),
    (
        "thread-daemon-join",
        "dalle_tpu/swarm/fake_audit_worker.py",
        """
import threading
class Auditor(threading.Thread):
    def __init__(self, dht, ledger):
        super().__init__()           # non-daemon, and stop() below
        self.dht = dht               # never joins: an in-flight fetch
        self._stop = threading.Event()   # races the DHT teardown
    def stop(self):
        self._stop.set()
""",
        """
import threading
class Auditor(threading.Thread):
    def __init__(self, dht, ledger):
        super().__init__(daemon=True, name="audit-worker")
        self.dht = dht
        self._stop = threading.Event()
    def stop(self, join_timeout=10.0):
        self._stop.set()
        if join_timeout is not None and self.is_alive() \\
                and threading.current_thread() is not self:
            self.join(timeout=join_timeout)
""",
    ),
    # Round-repair + proof-receipt shapes (swarm/repair.py +
    # audit.ProofVerifier via health.StrikeGossip): corrections fan out
    # through pools in plausible refactors, and the evidence replay
    # runs on the gossip worker against the native DHT — pin the
    # hazardous variant of each shape so the real code can never
    # regress into them unnoticed.
    (
        "unchecked-pool-future",
        "dalle_tpu/swarm/fake_repair.py",
        """
import concurrent.futures
def apply_corrections(plane, targets, patch):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        futs = [pool.submit(patch, t, a)
                for t, a in zip(targets, plane.drain())]
        concurrent.futures.wait(futs)   # a repair that FAILED to land
        # (the whole point of the plane) vanishes in an unread Future
""",
        """
import concurrent.futures
def apply_corrections(plane, targets, patch):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        futs = [pool.submit(patch, t, a)
                for t, a in zip(targets, plane.drain())]
        return sum(1 for f in futs if f.result())   # every landing read
""",
    ),
    (
        "thread-daemon-join",
        "dalle_tpu/swarm/fake_proof_worker.py",
        """
import threading
class ProofFolder(threading.Thread):
    def __init__(self, dht, verifier):
        super().__init__()           # non-daemon, and stop() below
        self.dht = dht               # never joins: an in-flight
        self.verifier = verifier     # evidence replay races the
        self._stop = threading.Event()   # native DHT teardown
    def stop(self):
        self._stop.set()
""",
        """
import threading
class ProofFolder(threading.Thread):
    def __init__(self, dht, verifier):
        super().__init__(daemon=True, name="proof-folder")
        self.dht = dht
        self.verifier = verifier
        self._stop = threading.Event()
    def stop(self, join_timeout=10.0):
        self._stop.set()
        if join_timeout is not None and self.is_alive() \\
                and threading.current_thread() is not self:
            self.join(timeout=join_timeout)
""",
    ),
    # v2 flow model: one fixture pair per formerly-documented blind spot
    # (LINTS.md "What the flow model tracks") — a true positive the v1
    # name-based model missed, and the sanctioned idiom staying quiet.
    (
        # blind spot: indirect wrapping (`wrap = jax.jit`)
        "use-after-donate",
        "dalle_tpu/fake_alias.py",
        """
import jax
wrap = jax.jit
def update(state, grads):
    return state
_step = wrap(update, donate_argnums=0)
def train(state, grads):
    _step(state, grads)              # aliased wrapper still donates...
    return state.loss                # ...and this reads the corpse
""",
        """
import jax
wrap = jax.jit
def update(state, grads):
    return state
_step = wrap(update, donate_argnums=0)
def train(state, grads):
    state = _step(state, grads)      # rebind: the sanctioned shape
    return state.loss
""",
    ),
    (
        # blind spot: closure capture of a donated binding
        "use-after-donate",
        "dalle_tpu/fake_closure.py",
        """
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
def train(state, grads):
    def peek():
        return state.loss            # captures `state`...
    _step(state, grads)              # ...which this donates...
    return peek()                    # ...and this reads the corpse
""",
        """
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
def train(state, grads):
    state = _step(state, grads)      # rebound BEFORE the capture:
    def peek():                      # the closure reads the live
        return state.loss            # result, not the donated buffer
    return peek()
""",
    ),
    (
        # blind spot: jit binding through a constructor parameter
        # (`self.apply_fn = apply_fn` — the trainer's
        # CollaborativeOptimizer shape)
        "use-after-donate",
        "dalle_tpu/fake_ctor.py",
        """
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
class Trainer:
    def __init__(self, apply_fn):
        self.apply_fn = apply_fn
    def train(self, state, grads):
        self.apply_fn(state, grads)  # donates through the ctor param...
        return state.loss            # ...then reads the corpse
def make():
    return Trainer(_step)
""",
        """
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
class Trainer:
    def __init__(self, apply_fn):
        self.apply_fn = apply_fn
    def train(self, state, grads):
        state = self.apply_fn(state, grads)   # rebind retires it
        return state.loss
def make():
    return Trainer(_step)
""",
    ),
    (
        # blind spot: key threaded through a lax.scan carry tuple (the
        # decode sampler's shape)
        "rng-key-reuse",
        "dalle_tpu/fake_scan.py",
        """
import jax
from jax import lax
def sample(cache, rng, xs):
    def step(carry, x):
        cache, rng = carry           # unpacked carry key is tracked
        a = jax.random.normal(rng, ())
        b = jax.random.uniform(rng, ())   # same key: correlated
        return (cache, rng), a + b
    return lax.scan(step, (cache, rng), xs)
""",
        """
import jax
from jax import lax
def sample(cache, rng, xs):
    def step(carry, x):
        cache, rng = carry
        rng, sub = jax.random.split(rng)   # split first: both fresh
        a = jax.random.normal(sub, ())
        return (cache, rng), a
    return lax.scan(step, (cache, rng), xs)
""",
    ),
    (
        # blind spot: base-class locks (inheritance not walked in v1)
        "lock-order-cycle",
        "dalle_tpu/fake_baselock.py",
        """
import threading
class Base:
    def __init__(self):
        self._head = threading.Lock()
        self._tail = threading.Lock()
    def push(self):
        with self._head:
            with self._tail:
                return 1
class Sub(Base):
    def pop(self):
        with self._tail:
            with self._head:         # inverted vs Base.push: the
                return 2             # subclass acquires the SAME locks
""",
        """
import threading
class Base:
    def __init__(self):
        self._head = threading.Lock()
        self._tail = threading.Lock()
    def push(self):
        with self._head:
            with self._tail:
                return 1
class Sub(Base):
    def pop(self):
        with self._head:
            with self._tail:         # same order: consistent
                return 2
""",
    ),
    (
        # the rule the v2 model newly enables: a donated binding that
        # ESCAPED (attribute/container/closure) before the donation —
        # the bug class a unified device-state substrate could
        # reintroduce (ROADMAP direction 5)
        "donated-escape",
        "dalle_tpu/fake_escape.py",
        """
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
class Loop:
    def run(self, state, grads):
        self._last = state           # escapes into an attribute...
        state = _step(state, grads)  # ...the donation deletes it...
        return self._last.loss       # ...and the holder reads garbage
def drain(state, grads, pending):
    pending.append(state)            # escapes into a container...
    state = _step(state, grads)
    return pending[0].loss           # ...read through the container
""",
        """
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
class Loop:
    def run(self, state, grads):
        state = _step(state, grads)
        self._last = state           # holds the REBOUND result: live
        return self._last.loss
def drain(state, grads, pending):
    state = _step(state, grads)
    pending.append(state)
    return pending[0].loss
def stash_then_clear(state, grads, pending):
    pending.append(state)
    pending = []                     # holder rebound before the
    state = _step(state, grads)      # donation: nothing stale
    return state.loss
""",
    ),
    (
        "mixed-lock-writes",
        "dalle_tpu/fake.py",
        """
import threading
class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
    def inc(self):
        with self._lock:
            self.n += 1
    def reset(self):
        self.n = 0           # races inc()'s locked writes
""",
        """
import threading
class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0           # __init__ publishes before threads exist
    def inc(self):
        with self._lock:
            self.n += 1
    def reset(self):
        with self._lock:
            self.n = 0
""",
    ),
    # r15 in-collective quantization shapes: the error-feedback residual
    # rides DONATED jitted programs (swarm/error_feedback.py), and the
    # fused owner accumulate drains per-sender device dispatches through
    # the decode pool (swarm/allreduce.py) — pin the hazardous variant
    # of each so the real paths can never regress into them unnoticed.
    (
        "use-after-donate",
        "dalle_tpu/swarm/fake_ef.py",
        """
import functools
import jax
@functools.partial(jax.jit, donate_argnums=(0,))
def _ef_add(resid, flat):
    return flat + resid
def compensate(resid, flat):
    _ef_add(resid, flat)            # residual donated, never rebound...
    return resid + flat             # ...then read through the corpse
""",
        """
import functools
import jax
@functools.partial(jax.jit, donate_argnums=(0,))
def _ef_add(resid, flat):
    return flat + resid
@functools.partial(jax.jit, donate_argnums=(0,))
def _ef_store(comp, segs):
    return comp - jax.numpy.concatenate(segs)
def round_residual(resid, flat, segs):
    comp = _ef_add(resid, flat)     # old residual consumed: rebind
    resid = _ef_store(comp, segs)   # comp consumed: never read again
    return resid
""",
    ),
    (
        "unchecked-pool-future",
        "dalle_tpu/swarm/fake_fused.py",
        """
import concurrent.futures
def drain_reduce(decode, raws, acc, fused_accumulate):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as dec_pool:
        futs = [dec_pool.submit(decode, r) for r in raws]
        concurrent.futures.wait(futs)   # a failed decode (bad codec,
    return acc                          # device error) vanishes unread
""",
        """
import concurrent.futures
def drain_reduce(decode, raws, acc, fused_accumulate):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as dec_pool:
        futs = [dec_pool.submit(decode, r) for r in raws]
        concurrent.futures.wait(futs)
        for f in futs:
            payloads = f.result()     # every decode surfaced, then the
            if payloads is not None:  # donated device accumulate rebinds
                acc = fused_accumulate(acc, payloads)
    return acc
""",
    ),
    (
        "blocking-io-under-lock",
        "dalle_tpu/fake_sink.py",
        """
import threading, time
class Sink:
    def __init__(self):
        self._lock = threading.Lock()
    def flush(self, path, row):
        with self._lock:
            f = open(path, "a")
            f.write(row)
            time.sleep(0.05)
def dump(path, rows):
    lk = threading.Lock()
    with lk:
        with open(path, "a") as f:
            f.writelines(rows)
def dump_single_header(path, rows):
    lk = threading.Lock()
    with lk, open(path, "a") as f:
        f.writelines(rows)
""",
        """
import threading, time
class Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending = []
    def add(self, row):
        with self._lock:
            self._pending.append(row)   # memory only: fine
    def flush(self, path):
        with self._lock:
            rows, self._pending = self._pending, []
        with open(path, "a") as f:     # I/O OUTSIDE the lock
            f.writelines(rows)
    def waiter(self):
        with self._cv:
            self._cv.wait(timeout=0.1)  # releases the lock: fine
def slow_helper(path):
    time.sleep(0.01)                   # no lock held: fine
    with open(path) as f:
        return f.read()
def open_before_lock(path):
    lk = threading.Lock()
    with open(path) as f, lk:          # open PRECEDES the acquire
        pass
""",
    ),
    # v3 race family: Eraser-style lockset over the thread-role graph.
    # The violating sides spawn a real Thread(target=...) so the state
    # is reachable from two roles; the idiomatic sides double as the
    # init-before-start exemption regression (the __init__ writes
    # BEFORE .start() never count as racy).
    (
        "shared-write-unlocked",
        "dalle_tpu/fake_race.py",
        """
import threading
class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0               # pre-start init: exempt
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
    def _run(self):
        while True:
            with self._lock:
                self.total += 1
    def reset(self):
        self.total = 0               # main-role write, no lock: races
""",
        """
import threading
class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0               # pre-start init: exempt
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
    def _run(self):
        while True:
            with self._lock:
                self.total += 1
    def reset(self):
        with self._lock:
            self.total = 0
""",
    ),
    (
        "lock-inconsistent-access",
        "dalle_tpu/fake_race.py",
        """
import threading
class Stats:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.rounds = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
    def _run(self):
        with self._a:
            self.rounds += 1
    def snapshot(self):
        with self._b:                # a lock, but not THE lock: the
            return self.rounds       # lockset intersection is empty
""",
        """
import threading
class Stats:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.rounds = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
    def _run(self):
        with self._a:
            self.rounds += 1
    def snapshot(self):
        with self._a:                # same lock everywhere
            return self.rounds
""",
    ),
]


@pytest.mark.parametrize(
    "rule,path,bad,good", FIXTURES,
    ids=[f"{r}-{i}" for i, (r, *_rest) in enumerate(FIXTURES)])
def test_rule_fixture(rule, path, bad, good):
    hits = analyze_source(bad, path=path, rules=[rule])
    assert hits, f"{rule} missed its violating fixture"
    assert all(f.rule == rule for f in hits)
    clean = analyze_source(good, path=path, rules=[rule])
    assert clean == [], (
        f"{rule} false-positived on idiomatic code: "
        f"{[f.format() for f in clean]}")


def test_every_rule_has_a_fixture():
    covered = {r for r, *_rest in FIXTURES}
    every = set(RULES) | set(PROJECT_RULES)
    assert covered == every, (
        "rules without fixtures rot silently: "
        f"missing {every - covered}")


def test_inline_suppression_same_and_previous_line():
    bad = """
import threading
def spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
"""
    assert analyze_source(bad, path="dalle_tpu/fake.py")
    same = bad.replace(
        "target=fn)", "target=fn)  # graftlint: disable=thread-daemon-join")
    assert analyze_source(same, path="dalle_tpu/fake.py") == []
    above = bad.replace(
        "    t = threading.Thread",
        "    # graftlint: disable=thread-daemon-join\n"
        "    t = threading.Thread")
    assert analyze_source(above, path="dalle_tpu/fake.py") == []
    # a directive for a DIFFERENT rule must not suppress
    wrong = bad.replace(
        "target=fn)", "target=fn)  # graftlint: disable=silent-except")
    assert analyze_source(wrong, path="dalle_tpu/fake.py")


def test_baseline_roundtrip_and_occurrence_fingerprints(tmp_path):
    src = """
def a(x):
    try:
        return x()
    except Exception:
        return None
def b(x):
    try:
        return x()
    except Exception:
        return None
"""
    findings = analyze_source(src, path="dalle_tpu/fake.py",
                              rules=["silent-except"])
    assert len(findings) == 2
    # identical snippets get distinct occurrence-indexed fingerprints
    fps = [fp for _f, fp in fingerprint_findings(findings)]
    assert len(set(fps)) == 2
    path = str(tmp_path / "baseline.json")
    save_baseline(path, findings)
    baseline = load_baseline(path)
    fresh, stale = diff_baseline(findings, baseline)
    assert fresh == [] and stale == set()
    # fixing one finding leaves a stale entry, adds nothing fresh
    fresh, stale = diff_baseline(findings[:1], baseline)
    assert fresh == [] and len(stale) == 1
    # a new finding in a different file is fresh
    moved = analyze_source(src, path="dalle_tpu/other.py",
                           rules=["silent-except"])
    fresh, _ = diff_baseline(moved, baseline)
    assert len(fresh) == 2


def test_parse_error_is_reported_not_raised():
    out = analyze_source("def broken(:\n", path="dalle_tpu/fake.py")
    assert [f.rule for f in out] == ["parse-error"]


# -- project model: cross-module resolution + call graph -------------------

_STEPS_SRC = """
import functools
import jax

@functools.partial(jax.jit, donate_argnums=0)
def apply_step(state, grads):
    return state

class Stepper:
    def __init__(self):
        self._fn = None
    def make(self):
        return jax.jit(apply_step, donate_argnums=0)
"""


def test_flow_rules_resolve_across_modules():
    """use-after-donate through a from-import alias AND a module alias:
    the donation fact lives in one file, the hazardous read in another."""
    bad_from = """
from dalle_tpu.fake_steps import apply_step as step
def train(state, grads):
    step(state, grads)
    return state.loss
"""
    bad_mod = """
import dalle_tpu.fake_steps as steps
def train(state, grads):
    steps.apply_step(state, grads)
    return state.loss
"""
    good = """
from dalle_tpu.fake_steps import apply_step as step
def train(state, grads):
    state = step(state, grads)
    return state.loss
"""
    for trainer in (bad_from, bad_mod):
        hits = analyze_sources(
            {"dalle_tpu/fake_steps.py": _STEPS_SRC,
             "dalle_tpu/fake_train.py": trainer},
            rules=["use-after-donate"])
        assert [f.rule for f in hits] == ["use-after-donate"], hits
        assert hits[0].path == "dalle_tpu/fake_train.py"
    clean = analyze_sources(
        {"dalle_tpu/fake_steps.py": _STEPS_SRC,
         "dalle_tpu/fake_train.py": good},
        rules=["use-after-donate"])
    assert clean == [], [f.format() for f in clean]


def test_project_symbol_table_and_partial_jit_recognition():
    """The call-graph substrate directly: import resolution (from-import
    alias, module alias) and the partial-jit decorator's donate_argnums
    landing in the function record and in donate_positions()."""
    from dalle_tpu.analysis.project import Project, summarize_source
    train_src = """
import dalle_tpu.fake_steps as steps
from dalle_tpu.fake_steps import apply_step as step
def train(state, grads):
    return state
"""
    summaries = {
        p: summarize_source(p, s)
        for p, s in (("dalle_tpu/fake_steps.py", _STEPS_SRC),
                     ("dalle_tpu/fake_train.py", train_src))}
    proj = Project(summaries)
    # partial-jit decorator recognized, donate position extracted
    rec = proj.function("dalle_tpu.fake_steps", "apply_step")
    assert rec["jit"] == {"donate": [0], "static": []}
    # from-import alias hop
    assert proj.resolve_callee(
        "dalle_tpu.fake_train", None, "train", "step") == (
        "fn", "dalle_tpu.fake_steps", "apply_step")
    # module-alias dotted call
    assert proj.resolve_callee(
        "dalle_tpu.fake_train", None, "train", "steps.apply_step") == (
        "fn", "dalle_tpu.fake_steps", "apply_step")
    # a flow-IR call op through the alias reports the donated position
    op = {"t": "call", "fn": "step", "inner": None, "jit": None,
          "args": ["state", "grads"], "l": 4}
    assert proj.donate_positions(
        "dalle_tpu.fake_train", None, "train", op) == [0]


# -- race family: happens-before seeds, escape hatches, thread roles ------

_RACE_RULES = ["shared-write-unlocked", "lock-inconsistent-access"]


def _race(src):
    return [(f.rule, f.line) for f in
            analyze_source(src, path="dalle_tpu/fake_race.py",
                           rules=_RACE_RULES)]


def test_race_post_join_exemption():
    """A read AFTER .join() has a happens-before edge to every write
    the joined thread made — the classic fork/join result pickup must
    stay quiet, and deleting the join must flag the thread's write."""
    good = """
import threading
class Once:
    def __init__(self):
        self.result = None
        self._t = threading.Thread(target=self._run)
    def _run(self):
        self.result = 42
    def wait(self):
        self._t.start()
        self._t.join()
        return self.result
"""
    assert _race(good) == []
    racy = good.replace("        self._t.join()\n", "")
    assert _race(racy) == [("shared-write-unlocked", 8)]


def test_race_queue_handoff_is_exempt():
    """Synchronized container types (queue.Queue and friends) ARE the
    happens-before mechanism — attributes holding one never race."""
    src = """
import threading
import queue
class Pipe:
    def __init__(self):
        self.q = queue.Queue()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
    def _run(self):
        self.q.put(1)
    def take(self):
        return self.q.get()
"""
    assert _race(src) == []


def test_race_handoff_annotation():
    """`# graftlint: handoff=<mechanism>` on the init site declares a
    protocol-level happens-before the lockset can't see; without it the
    same shape is flagged at every unlocked access."""
    noted = """
import threading
class Batch:
    def __init__(self):
        self.buf = []  # graftlint: handoff=drained-before-start
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
    def _run(self):
        self.buf.append(1)
    def drain(self):
        out, self.buf = self.buf, []
        return out
"""
    assert _race(noted) == []
    bare = noted.replace("  # graftlint: handoff=drained-before-start",
                         "")
    assert [r for r, _l in _race(bare)] == \
        ["shared-write-unlocked", "shared-write-unlocked"]


def test_race_guarded_by_annotation():
    """`# graftlint: guarded-by=<lock>` asserts every access happens
    under that lock — the declared guard joins every lockset, so the
    intersection can never come up empty for this attribute."""
    noted = """
import threading
class Mirror:
    def __init__(self):
        self._lock = threading.Lock()
        self.view = {"a": 1}  # graftlint: guarded-by=_lock
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
    def _run(self):
        self.view = {"b": 2}
    def read(self):
        return self.view
"""
    assert _race(noted) == []
    bare = noted.replace('  # graftlint: guarded-by=_lock', "")
    assert _race(bare) == [("shared-write-unlocked", 10)]


_ROLE_WORKER = """
import threading
_lock = threading.Lock()
pending = []
def loop():
    global pending
    pending = []
def flush():
    global pending
    with _lock:
        pending = [1]
def helper():
    loop()
"""

_ROLE_SPAWNER = """
import threading
from pkg.worker import loop, flush
def boot():
    t = threading.Thread(target=loop, daemon=True)
    t.start()
    flush()
    return t
"""


def test_thread_role_pass_on_lowered_ir():
    """The role substrate directly: cross-module Thread(target=...)
    discovery, role flooding along the call graph (a function called
    from a role-less caller ALSO carries "main" — dual-role), and the
    spawner->target file dependency edge --diff consumes."""
    from dalle_tpu.analysis.project import Project, summarize_source
    srcs = {"pkg/worker.py": _ROLE_WORKER,
            "pkg/spawner.py": _ROLE_SPAWNER}
    proj = Project({p: summarize_source(p, s) for p, s in srcs.items()},
                   srcs)
    assert proj.thread_entries() == [
        ("pkg.worker:loop", ("pkg.worker", "loop"))]
    roles = proj.thread_roles()
    # entry function runs under its own role AND main (helper calls it)
    assert roles[("pkg.worker", "loop")] == {"main", "pkg.worker:loop"}
    assert roles[("pkg.worker", "flush")] == {"main"}
    assert roles[("pkg.spawner", "boot")] == {"main"}
    assert proj.spawn_dependencies() == {
        "pkg/spawner.py": {"pkg/worker.py"}}


def test_diff_scope_expands_with_spawn_dependencies(tmp_path):
    """--diff semantics for whole-program verdicts: editing only the
    SPAWNER must still surface the race findings it induces in the
    (textually unchanged) target module — role assignment is whole-
    program, so the changed set expands by its spawn-dependency
    closure. An unrelated changed set reports nothing."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "worker.py").write_text(_ROLE_WORKER)
    (pkg / "spawner.py").write_text(_ROLE_SPAWNER)
    full = analyze_paths([str(pkg)], root=str(tmp_path))
    assert [(f.rule, f.path) for f in full] == \
        [("shared-write-unlocked", "pkg/worker.py")]
    diff = analyze_paths([str(pkg)], root=str(tmp_path),
                         changed_only={"pkg/spawner.py"})
    assert [(f.rule, f.path) for f in diff] == \
        [("shared-write-unlocked", "pkg/worker.py")]
    assert analyze_paths([str(pkg)], root=str(tmp_path),
                         changed_only=set()) == []


# Mutation sensitivity on the REAL modules lives in the corpus now:
# tests/mutation_corpus/ + tests/test_mutation_corpus.py generalize the
# old single engine-loop mutation test to >= 1 injection per flow rule.


def test_parse_cache_keeps_warm_scan_in_budget(tmp_path):
    """CI mechanics: a warm full scan (all summaries + findings cache-
    hit, only the project pass recomputed) stays inside the ~2 s r7
    cold-scan budget on the 2-core box. min-of-2 because this machine's
    timings wobble under co-tenant load."""
    cache = str(tmp_path / "cache.json")
    target = os.path.join(REPO, "dalle_tpu")
    cold = analyze_paths([target], root=REPO, cache_path=cache)
    warm_times = []
    for _ in range(2):
        t0 = time.monotonic()
        warm = analyze_paths([target], root=REPO, cache_path=cache)
        warm_times.append(time.monotonic() - t0)
        assert warm == cold          # the cache changes nothing observable
    assert min(warm_times) < 2.0, warm_times


def test_scoped_scan_preserves_out_of_scope_cache(tmp_path):
    """A path-restricted run (`lint.py dalle_tpu/serving`) shares the
    cache file with the full --check; it must not evict the entries it
    never looked at (that silently turns the next pre-commit scan
    cold)."""
    import json
    cache = str(tmp_path / "cache.json")
    analyze_paths([os.path.join(REPO, "dalle_tpu")], root=REPO,
                  cache_path=cache)
    with open(cache) as fh:
        full = set(json.load(fh)["files"])
    analyze_paths([os.path.join(REPO, "dalle_tpu", "serving")],
                  root=REPO, cache_path=cache)
    with open(cache) as fh:
        after = set(json.load(fh)["files"])
    assert after == full, sorted(full - after)[:5]


def test_machine_output_fingerprints_are_baseline_stable():
    """JSON/SARIF fingerprints must match the ones diff_baseline pins:
    computed over the FULL finding list, with the unbaselined remainder
    selected by exclusion — fingerprinting only the fresh subset would
    renumber the occurrence index and a fresh duplicate would emit its
    baselined twin's fingerprint."""
    import json
    from dalle_tpu.analysis import sarif
    src = """
def a(x):
    try:
        return x()
    except Exception:
        return None
def b(x):
    try:
        return x()
    except Exception:
        return None
"""
    findings = analyze_source(src, path="dalle_tpu/fake.py",
                              rules=["silent-except"])
    pairs = fingerprint_findings(findings)
    assert len(pairs) == 2
    baseline = {pairs[0][1]}             # first duplicate triaged
    fresh, _ = diff_baseline(findings, baseline)
    assert len(fresh) == 1
    out = json.loads(sarif.to_json(findings,
                                   exclude_fingerprints=baseline))
    assert [d["fingerprint"] for d in out["findings"]] == [pairs[1][1]]
    doc = json.loads(sarif.to_sarif(findings,
                                    exclude_fingerprints=baseline))
    results = doc["runs"][0]["results"]
    assert [r["partialFingerprints"]["graftlint/v1"] for r in results] \
        == [pairs[1][1]]


# -- parse cache under the split-version schema ----------------------------

_CACHE_PKG = {
    "pkg/steps.py": """
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
def train(state, grads):
    _step(state, grads)
    return state.loss
""",
    "pkg/handlers.py": """
def recv(sock):
    try:
        return sock.recv()
    except Exception:
        return None
""",
}


def _cache_scan(tmp_path, cache_name="cache.json", stats=None):
    import os as _os
    root = str(tmp_path)
    for rel, src in _CACHE_PKG.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return analyze_paths([_os.path.join(root, "pkg")], root=root,
                         cache_path=str(tmp_path / cache_name),
                         stats=stats)


def test_cache_corrupt_and_foreign_files_are_discarded(tmp_path):
    """An unreadable or structurally foreign cache file must be ignored
    wholesale — never trusted, never a crash."""
    import json
    cold = _cache_scan(tmp_path)
    assert {f.rule for f in cold} == {"use-after-donate", "silent-except"}
    cache = tmp_path / "cache.json"
    for poison in ("{not json", json.dumps({"something": "else"}),
                   json.dumps({"format": 2, "files": "nope"}),
                   json.dumps({"format": 99, "files": {}})):
        cache.write_text(poison)
        stats = {}
        again = _cache_scan(tmp_path, stats=stats)
        assert again == cold
        assert stats["cache"]["hits"] == 0      # poison bought nothing


def test_cache_schema_bump_keeps_per_file_findings(tmp_path):
    """The split version key: a summary-schema change discards flow
    summaries but NOT the per-file findings of unchanged rules — the
    re-scan after a flow-model upgrade pays only the summarize half.
    A rules-key change does the inverse."""
    import json
    cold = _cache_scan(tmp_path)
    cache = tmp_path / "cache.json"

    data = json.loads(cache.read_text())
    assert all("findings" in e and "summary" in e
               for e in data["files"].values())

    # schema bump: summaries invalidated, findings kept
    data["schema_key"] = "stale-schema"
    cache.write_text(json.dumps(data))
    stats = {}
    warm = _cache_scan(tmp_path, stats=stats)
    assert warm == cold
    assert stats["cache"]["misses"] == len(_CACHE_PKG)
    assert stats["cache"]["partial"] == len(_CACHE_PKG)
    # no per-file rule ran again: their timing ledger is empty
    per_file_rules = set(RULES)
    assert not (set(stats["rules"]) & per_file_rules
                and any(stats["rules"][r]["seconds"] > 0
                        for r in set(stats["rules"]) & per_file_rules))

    # rules-key bump: findings invalidated, summaries kept
    data = json.loads(cache.read_text())
    data["rules_key"] = "stale-rules"
    cache.write_text(json.dumps(data))
    stats = {}
    warm = _cache_scan(tmp_path, stats=stats)
    assert warm == cold
    assert stats["cache"]["partial"] == len(_CACHE_PKG)

    # untouched: full hits, nothing recomputed
    stats = {}
    warm = _cache_scan(tmp_path, stats=stats)
    assert warm == cold
    assert stats["cache"]["hits"] == len(_CACHE_PKG)
    assert stats["cache"]["misses"] == 0


# -- CLI: stale-baseline enforcement + --prune-stale ------------------------

def _lint_cli():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graftlint_cli", os.path.join(REPO, "scripts", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_fails_on_stale_baseline_entries(tmp_path, capsys):
    """A baselined finding that no longer exists is a FIXED finding: the
    ratchet must shrink in the same commit, so --check fails until
    --prune-stale (or --write-baseline) removes the entry."""
    import json
    cli = _lint_cli()
    cache = str(tmp_path / "cache.json")
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"version": 1, "findings": [
        {"rule": "silent-except", "path": "dalle_tpu/gone.py",
         "line": 1, "snippet": "except Exception:",
         "fingerprint": "feedfacefeedface"}]}))
    rc = cli.main(["--check", "--baseline", str(baseline),
                   "--cache", cache])
    out = capsys.readouterr().out
    assert rc == 1
    assert "stale baseline" in out and "--prune-stale" in out
    # --prune-stale drops the dead entry, then --check goes green
    rc = cli.main(["--prune-stale", "--baseline", str(baseline),
                   "--cache", cache])
    assert rc == 0
    assert json.loads(baseline.read_text())["findings"] == []
    rc = cli.main(["--check", "--baseline", str(baseline),
                   "--cache", cache])
    assert rc == 0
    # scoped runs still only NOTE staleness (out-of-scope entries are
    # invisible, not fixed) — same baseline, restricted path
    baseline.write_text(json.dumps({"version": 1, "findings": [
        {"rule": "silent-except", "path": "dalle_tpu/gone.py",
         "line": 1, "snippet": "except Exception:",
         "fingerprint": "feedfacefeedface"}]}))
    rc = cli.main(["--check", "--baseline", str(baseline),
                   "--cache", cache,
                   os.path.join(REPO, "dalle_tpu", "analysis")])
    assert rc == 0
    # and --prune-stale refuses a restricted scope outright
    rc = cli.main(["--prune-stale", "--baseline", str(baseline),
                   "--cache", cache,
                   os.path.join(REPO, "dalle_tpu", "analysis")])
    assert rc == 2


def test_json_format_reports_per_rule_stats(tmp_path, capsys):
    """--format json carries the per-rule finding/timing ledger so a new
    rule's CI budget cost is visible the day it lands."""
    import json
    cli = _lint_cli()
    rc = cli.main(["--format", "json",
                   "--cache", str(tmp_path / "cache.json")])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    stats = doc["stats"]
    assert set(stats["cache"]) == {"hits", "partial", "misses"}
    for rid in ("use-after-donate", "donated-escape", "lock-order-cycle",
                "rng-key-reuse", "shared-write-unlocked",
                "lock-inconsistent-access"):
        assert rid in stats["rules"]
        assert set(stats["rules"][rid]) == {"findings", "seconds"}


# -- SARIF golden ----------------------------------------------------------

_SARIF_FIXTURE = {
    "dalle_tpu/fake_sarif.py": """
import jax
def update(state, grads):
    return state
_step = jax.jit(update, donate_argnums=0)
def train(state, grads):
    _step(state, grads)
    return state.loss
def recv_a(sock):
    try:
        return sock.recv()
    except Exception:
        return None
def recv_b(sock):
    try:
        return sock.recv()
    except Exception:  # graftlint: disable=silent-except
        return None
""",
}


def test_sarif_output_matches_golden():
    """The SARIF 2.1.0 shape CI annotators rely on, pinned: rule
    metadata under tool.driver.rules, severity->level mapping (error
    rule vs warning rule), inline suppressions excluded, baselined
    fingerprints excluded, stable partialFingerprints."""
    import json
    from dalle_tpu.analysis import sarif
    findings = analyze_sources(
        dict(_SARIF_FIXTURE),
        rules=["use-after-donate", "silent-except"])
    # recv_b's handler is inline-suppressed: it must already be gone
    assert sorted(f.rule for f in findings) == [
        "silent-except", "use-after-donate"]
    doc = json.loads(sarif.to_sarif(findings))
    golden_path = os.path.join(REPO, "tests", "golden",
                               "graftlint_fixture.sarif.json")
    with open(golden_path, "r", encoding="utf-8") as fh:
        golden = json.load(fh)
    assert doc == golden
    # excluding the baselined fingerprint drops its result AND its rule
    # metadata row
    pairs = fingerprint_findings(findings)
    donate_fp = [fp for f, fp in pairs if f.rule == "use-after-donate"]
    doc2 = json.loads(sarif.to_sarif(
        findings, exclude_fingerprints=frozenset(donate_fp)))
    results = doc2["runs"][0]["results"]
    assert [r["ruleId"] for r in results] == ["silent-except"]
    assert [r["id"] for r in
            doc2["runs"][0]["tool"]["driver"]["rules"]] \
        == ["silent-except"]


_RACE_SARIF_SRC = """
import threading
class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
    def _run(self):
        while True:
            with self._lock:
                self.total += 1
    def reset(self):
        self.total = 0
"""


def test_race_sarif_output_matches_golden():
    """The race family's machine face, pinned: rule metadata row,
    error-level mapping, the counter-access context in the message, and
    a stable fingerprint — CI annotators key on all four."""
    import json
    from dalle_tpu.analysis import sarif
    findings = analyze_sources(
        {"dalle_tpu/fake_race_sarif.py": _RACE_SARIF_SRC},
        rules=["shared-write-unlocked"])
    assert [f.rule for f in findings] == ["shared-write-unlocked"]
    doc = json.loads(sarif.to_sarif(findings))
    golden_path = os.path.join(REPO, "tests", "golden",
                               "graftlint_race.sarif.json")
    with open(golden_path, "r", encoding="utf-8") as fh:
        golden = json.load(fh)
    assert doc == golden


def test_repo_scan_is_clean_against_baseline():
    """The tier-1 enforcement face: dalle_tpu/ has zero unbaselined
    findings. New hazards must be fixed, suppressed with a justified
    inline disable, or consciously triaged into lint_baseline.json."""
    t0 = time.monotonic()
    findings = analyze_paths([os.path.join(REPO, "dalle_tpu")], root=REPO)
    baseline = load_baseline(os.path.join(REPO, "lint_baseline.json"))
    fresh, _stale = diff_baseline(findings, baseline)
    elapsed = time.monotonic() - t0
    assert not fresh, (
        "unbaselined graftlint findings (fix, suppress with a justified "
        "'# graftlint: disable=<rule>', or triage via scripts/lint.py "
        "--write-baseline):\n"
        + "\n".join(f"  {f.format()}\n      {f.snippet}" for f in fresh))
    # parse-only over ~70 files; the 15 s bound is generous even for the
    # 2-core box, and catches anyone wiring subprocess fan-out in here
    assert elapsed < 15.0, f"lint scan took {elapsed:.1f}s"
    assert not any(f.rule == "parse-error" for f in findings)
