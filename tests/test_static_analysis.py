"""graftlint (dalle_tpu/analysis): per-rule positive/negative fixtures,
suppression + baseline mechanics, and the tier-1 enforcement scan of the
real codebase against lint_baseline.json.

The fixtures are the rules' regression harness: every rule must catch
its violating snippet AND stay quiet on the idiomatic equivalent, so a
refactor of the analyzer cannot silently lobotomize a rule. The repo
scan is the enforcement face: any new unbaselined finding fails tier-1.

Everything here is stdlib-ast work over in-memory strings plus one parse
pass of ~70 files — no subprocesses, no jax tracing — so the whole
module runs in low single-digit seconds on the 2-core CI box.
"""

import os
import time

import pytest

from dalle_tpu.analysis import (RULES, analyze_paths, analyze_source,
                                diff_baseline, fingerprint_findings,
                                load_baseline, save_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (rule, fixture path, violating source, idiomatic source). The path
# matters for module-role rules: device-module fixtures pretend to live
# under dalle_tpu/ops/, quant fixtures in a quant module.
FIXTURES = [
    (
        "host-sync-in-jit",
        "dalle_tpu/fake.py",
        """
import jax
@jax.jit
def f(x):
    return float(x) + x.item()
""",
        """
import jax
import jax.numpy as jnp
@jax.jit
def f(x):
    return x.astype(jnp.float32) + jnp.sum(x)
def host_helper(x):
    return float(x)  # not traced: fine
""",
    ),
    (
        "host-sync-in-jit",
        "dalle_tpu/fake_pallas.py",
        """
from jax.experimental import pallas as pl
def _kern(x_ref, o_ref):
    o_ref[:] = x_ref[:].tolist()
def call(x):
    return pl.pallas_call(_kern, out_shape=None)(x)
""",
        """
from jax.experimental import pallas as pl
def _kern(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0
def call(x):
    return pl.pallas_call(_kern, out_shape=None)(x)
""",
    ),
    (
        "python-rng-in-device",
        "dalle_tpu/ops/fake.py",
        """
import numpy as np
def init_mask(shape):
    return np.random.rand(*shape) > 0.5
""",
        """
import numpy as np
import jax
def init_mask(key, seed, shape):
    rng = np.random.default_rng(seed)      # seeded: reproducible
    jmask = jax.random.bernoulli(key, 0.5, shape)
    return rng, jmask
""",
    ),
    (
        "python-rng-in-device",
        "dalle_tpu/fake.py",
        """
import jax, random
@jax.jit
def f(x):
    return x * random.random()
""",
        """
import jax
@jax.jit
def f(key, x):
    return x * jax.random.uniform(key)
""",
    ),
    (
        "nondet-pytree",
        "dalle_tpu/fake.py",
        """
import jax, time
@jax.jit
def f(x):
    return x + time.time()
""",
        """
import jax
@jax.jit
def f(x, now):
    return x + now          # wall clock rides in as an operand
""",
    ),
    (
        "nondet-pytree",
        "dalle_tpu/fake.py",
        """
import jax
@jax.jit
def f(tree):
    return [tree[k] for k in {"w", "b"}]
""",
        """
import jax
@jax.jit
def f(tree):
    return [tree[k] for k in sorted(tree)]   # deterministic order
""",
    ),
    (
        "literal-divisor-in-quant",
        "dalle_tpu/ops/pallas/fake_quant.py",
        """
import jax.numpy as jnp
def encode(absmax):
    scales = absmax / 127.0
    return scales
""",
        """
import jax.numpy as jnp
def encode(absmax, d127):
    scales = absmax / d127   # divisor rides as a runtime operand
    return scales
""",
    ),
    (
        "host-sync-in-hot-loop",
        "dalle_tpu/serving/fake.py",
        """
import numpy as np
import jax
def serve_loop(state, chunk_fn, total):
    while True:
        state = chunk_fn(state)
        pos = np.asarray(state.pos)          # blocking pull per chunk
        done = int(pos[0]) >= total
        flags = jax.device_get(state.flags)
        depth = state.depth.item()
        if done:
            break
""",
        """
import numpy as np
def _harvest(state, slot):
    return np.asarray(state.codes[slot])     # per-completion, no loop
def serve_loop(state, chunk_fn, pos_host, chunk, total):
    rows = []
    while True:
        state = chunk_fn(state)
        pos_host[:] = np.minimum(pos_host + chunk, total)  # host mirror
        if pos_host[0] >= total:
            rows.append(_harvest(state, 0))
            break
    n = int(np.asarray(rows).sum())          # outside the loop: fine
    return rows, n
""",
    ),
    (
        "silent-except",
        "dalle_tpu/swarm/fake.py",
        """
def recv_round(sock):
    try:
        return sock.recv()
    except Exception:
        return None
""",
        """
import logging
logger = logging.getLogger(__name__)
def recv_round(sock):
    try:
        return sock.recv()
    except Exception:
        logger.warning("round recv failed", exc_info=True)
        return None
def parse_port(s):
    try:
        return int(s)
    except ValueError:       # narrow except: deliberate, passes
        return None
""",
    ),
    (
        "blocking-in-async",
        "dalle_tpu/fake.py",
        """
import time
async def pump(queue):
    time.sleep(0.5)
    return await queue.get()
""",
        """
import asyncio, time
async def pump(queue, executor):
    def _worker():           # runs on the executor, not the loop:
        time.sleep(0.5)      # nested sync defs are NOT the coroutine
    await asyncio.get_event_loop().run_in_executor(executor, _worker)
    await asyncio.sleep(0.5)
    return await queue.get()
""",
    ),
    (
        "thread-daemon-join",
        "dalle_tpu/fake.py",
        """
import threading
def spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t
""",
        """
import threading
class Owner:
    def __init__(self, fn):
        self._thread = threading.Thread(target=fn, daemon=True)
    def start(self):
        self._thread.start()
    def stop(self):
        self._thread.join(timeout=5.0)
def spawn_joined(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()
    return t
""",
    ),
    (
        "thread-daemon-join",
        "dalle_tpu/fake_subclass.py",
        """
import threading
class Worker(threading.Thread):
    def __init__(self, fn):
        super().__init__()
        self.fn = fn
""",
        """
import threading
class Worker(threading.Thread):
    def __init__(self, fn):
        super().__init__(daemon=True, name="worker")
        self.fn = fn
""",
    ),
    (
        "unchecked-pool-future",
        "dalle_tpu/swarm/fake.py",
        """
import concurrent.futures
def scatter(work, items):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        pool.submit(work, items[0])                 # fire-and-forget
        futs = [pool.submit(work, it) for it in items]
        concurrent.futures.wait(futs)               # observes, never reads
""",
        """
import concurrent.futures
def scatter(work, items, log):
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        one = pool.submit(work, items[0])
        one.add_done_callback(log)
        futs = [pool.submit(work, it) for it in items]
        done, straggling = concurrent.futures.wait(futs, timeout=5.0)
        failed = sum(1 for f in done
                     if f.exception() is not None or not f.result())
        retry_futs = [pool.submit(work, it) for it in items[:failed]]
        for f in retry_futs:
            f.result()
        handed_off = [pool.submit(work, it) for it in items]
        return handed_off                # escapes: the caller consumes
""",
    ),
    (
        "mixed-lock-writes",
        "dalle_tpu/fake.py",
        """
import threading
class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
    def inc(self):
        with self._lock:
            self.n += 1
    def reset(self):
        self.n = 0           # races inc()'s locked writes
""",
        """
import threading
class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0           # __init__ publishes before threads exist
    def inc(self):
        with self._lock:
            self.n += 1
    def reset(self):
        with self._lock:
            self.n = 0
""",
    ),
]


@pytest.mark.parametrize(
    "rule,path,bad,good", FIXTURES,
    ids=[f"{r}-{i}" for i, (r, *_rest) in enumerate(FIXTURES)])
def test_rule_fixture(rule, path, bad, good):
    hits = analyze_source(bad, path=path, rules=[rule])
    assert hits, f"{rule} missed its violating fixture"
    assert all(f.rule == rule for f in hits)
    clean = analyze_source(good, path=path, rules=[rule])
    assert clean == [], (
        f"{rule} false-positived on idiomatic code: "
        f"{[f.format() for f in clean]}")


def test_every_rule_has_a_fixture():
    covered = {r for r, *_rest in FIXTURES}
    assert covered == set(RULES), (
        "rules without fixtures rot silently: "
        f"missing {set(RULES) - covered}")


def test_inline_suppression_same_and_previous_line():
    bad = """
import threading
def spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
"""
    assert analyze_source(bad, path="dalle_tpu/fake.py")
    same = bad.replace(
        "target=fn)", "target=fn)  # graftlint: disable=thread-daemon-join")
    assert analyze_source(same, path="dalle_tpu/fake.py") == []
    above = bad.replace(
        "    t = threading.Thread",
        "    # graftlint: disable=thread-daemon-join\n"
        "    t = threading.Thread")
    assert analyze_source(above, path="dalle_tpu/fake.py") == []
    # a directive for a DIFFERENT rule must not suppress
    wrong = bad.replace(
        "target=fn)", "target=fn)  # graftlint: disable=silent-except")
    assert analyze_source(wrong, path="dalle_tpu/fake.py")


def test_baseline_roundtrip_and_occurrence_fingerprints(tmp_path):
    src = """
def a(x):
    try:
        return x()
    except Exception:
        return None
def b(x):
    try:
        return x()
    except Exception:
        return None
"""
    findings = analyze_source(src, path="dalle_tpu/fake.py",
                              rules=["silent-except"])
    assert len(findings) == 2
    # identical snippets get distinct occurrence-indexed fingerprints
    fps = [fp for _f, fp in fingerprint_findings(findings)]
    assert len(set(fps)) == 2
    path = str(tmp_path / "baseline.json")
    save_baseline(path, findings)
    baseline = load_baseline(path)
    fresh, stale = diff_baseline(findings, baseline)
    assert fresh == [] and stale == set()
    # fixing one finding leaves a stale entry, adds nothing fresh
    fresh, stale = diff_baseline(findings[:1], baseline)
    assert fresh == [] and len(stale) == 1
    # a new finding in a different file is fresh
    moved = analyze_source(src, path="dalle_tpu/other.py",
                           rules=["silent-except"])
    fresh, _ = diff_baseline(moved, baseline)
    assert len(fresh) == 2


def test_parse_error_is_reported_not_raised():
    out = analyze_source("def broken(:\n", path="dalle_tpu/fake.py")
    assert [f.rule for f in out] == ["parse-error"]


def test_repo_scan_is_clean_against_baseline():
    """The tier-1 enforcement face: dalle_tpu/ has zero unbaselined
    findings. New hazards must be fixed, suppressed with a justified
    inline disable, or consciously triaged into lint_baseline.json."""
    t0 = time.monotonic()
    findings = analyze_paths([os.path.join(REPO, "dalle_tpu")], root=REPO)
    baseline = load_baseline(os.path.join(REPO, "lint_baseline.json"))
    fresh, _stale = diff_baseline(findings, baseline)
    elapsed = time.monotonic() - t0
    assert not fresh, (
        "unbaselined graftlint findings (fix, suppress with a justified "
        "'# graftlint: disable=<rule>', or triage via scripts/lint.py "
        "--write-baseline):\n"
        + "\n".join(f"  {f.format()}\n      {f.snippet}" for f in fresh))
    # parse-only over ~70 files; the 15 s bound is generous even for the
    # 2-core box, and catches anyone wiring subprocess fan-out in here
    assert elapsed < 15.0, f"lint scan took {elapsed:.1f}s"
    assert not any(f.rule == "parse-error" for f in findings)
