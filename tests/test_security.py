"""Adversarial swarm tests: forged confirmations, spoofed identities,
injected data-plane chunks, and store flooding (VERDICT r1 weak #8,
ADVICE r1)."""

import hashlib
import threading
import time

import msgpack
import numpy as np

from dalle_tpu.swarm import DHT, Identity
from dalle_tpu.swarm.allreduce import (_make_frame, _sign_ctx, _tag,
                                       flatten_tensors, run_allreduce)
from dalle_tpu.swarm import compression
from dalle_tpu.swarm.dht import get_dht_time, owner_bound_peer_id
from dalle_tpu.swarm.matchmaking import (_confirm_tag, _signed_confirmation,
                                         GroupMember, make_group,
                                         verify_confirmation)


def make_swarm(n, **kwargs):
    nodes = []
    for _ in range(n):
        peers = [nodes[0].visible_address] if nodes else []
        nodes.append(DHT(initial_peers=peers, identity=Identity.generate(),
                         rpc_timeout=2.0, **kwargs))
    return nodes


class TestConfirmationSigning:
    def _members(self, ids):
        return [GroupMember(i, f"127.0.0.1:{p}", 1.0)
                for i, p in zip(ids, range(40000, 40000 + len(ids)))]

    def test_valid_confirmation_roundtrip(self):
        leader = Identity.generate()
        leader_id = hashlib.sha256(leader.public_bytes).hexdigest()
        members = self._members([leader_id, "b" * 64])
        raw = _signed_confirmation(leader, "p", 3, members)
        verified = verify_confirmation(raw, "p", 3, leader_id)
        assert verified is not None
        got, _keys = verified
        assert [m.peer_id for m in got] == [m.peer_id for m in members]

    def test_forged_signer_rejected(self):
        leader = Identity.generate()
        attacker = Identity.generate()
        leader_id = hashlib.sha256(leader.public_bytes).hexdigest()
        members = self._members([leader_id, "b" * 64])
        forged = _signed_confirmation(attacker, "p", 3, members)
        assert verify_confirmation(forged, "p", 3, leader_id) is None

    def test_wrong_epoch_rejected(self):
        leader = Identity.generate()
        leader_id = hashlib.sha256(leader.public_bytes).hexdigest()
        raw = _signed_confirmation(leader, "p", 3,
                                   self._members([leader_id]))
        assert verify_confirmation(raw, "p", 4, leader_id) is None

    def test_unsigned_payload_rejected(self):
        leader = Identity.generate()
        leader_id = hashlib.sha256(leader.public_bytes).hexdigest()
        legacy = msgpack.packb([[leader_id, "127.0.0.1:1", 1.0]])
        assert verify_confirmation(legacy, "p", 3, leader_id) is None

    def test_follower_ignores_forged_roster(self):
        """An attacker pushing a roster that excludes a member cannot eject
        it: the forged confirmation fails verification and the follower
        keeps its own DHT view (which includes itself)."""
        nodes = make_swarm(3)
        try:
            ids = sorted(n.peer_id for n in nodes)
            follower = next(n for n in nodes if n.peer_id != ids[0])
            attacker = next(n for n in nodes
                            if n.peer_id not in (ids[0], follower.peer_id))
            # attacker floods the follower's confirm tag with a roster that
            # excludes it, signed by the attacker (not the leader)
            fake = _signed_confirmation(
                attacker.identity, "sec1", 0,
                [GroupMember(attacker.peer_id,
                             attacker.visible_address, 1.0)])
            stop = threading.Event()

            def flood():
                while not stop.is_set():
                    attacker.send(follower.visible_address,
                                  _confirm_tag("sec1", 0, follower.peer_id),
                                  fake, timeout=1.0)
                    time.sleep(0.05)

            t = threading.Thread(target=flood, daemon=True)
            t.start()
            try:
                groups = {}

                def run(node):
                    groups[node.peer_id] = make_group(
                        node, "sec1", 0, weight=1.0, matchmaking_time=2.0,
                        min_group_size=3)

                threads = [threading.Thread(target=run, args=(n,))
                           for n in nodes]
                for th in threads:
                    th.start()
                for th in threads:
                    th.join(timeout=30)
            finally:
                stop.set()
                t.join(timeout=5)
            g = groups[follower.peer_id]
            assert g is not None
            assert any(m.peer_id == follower.peer_id for m in g.members)
        finally:
            for n in nodes:
                n.shutdown()


class TestIdentityBinding:
    def test_spoofed_subkey_dropped(self):
        attacker = Identity.generate()
        victim_id = "ab" * 32
        marker = b"[owner:" + attacker.public_bytes.hex().encode() + b"]"
        assert owner_bound_peer_id(victim_id.encode() + marker) is None

    def test_validated_swarm_rejects_unsigned_identity(self):
        """In a swarm that signs its records, an UNSIGNED record claiming
        any identity must be rejected too — otherwise skipping the
        signature altogether bypasses the spoofing defense."""
        from dalle_tpu.swarm.metrics import make_validators

        ident = Identity.generate()
        node = DHT(identity=ident,
                   record_validators=make_validators(ident, "x"))
        open_node = DHT(identity=Identity.generate())
        try:
            assert node.signature_enforced
            assert node.bound_peer_id(b"fabricated-id") is None
            # its own signed records still bind
            marker = b"[owner:" + ident.public_bytes.hex().encode() + b"]"
            assert node.bound_peer_id(
                node.peer_id.encode() + marker) == node.peer_id
            # open swarms (no validator) keep accepting bare ids
            assert not open_node.signature_enforced
            assert open_node.bound_peer_id(b"plain") == "plain"
        finally:
            node.shutdown()
            open_node.shutdown()

    def test_scatter_chunk_bound_to_receiver(self):
        """An insider cannot cross-feed one member's scatter chunk to a
        different part owner: the signature binds the intended receiver."""
        from dalle_tpu.swarm.allreduce import _verify_frame
        from dalle_tpu.swarm.matchmaking import (AveragingGroup,
                                                 group_hash_of)

        sender = Identity.generate()
        sender_id = hashlib.sha256(sender.public_bytes).hexdigest()
        members = [GroupMember(sender_id, "a:1", 1.0),
                   GroupMember("r1", "a:2", 1.0),
                   GroupMember("r2", "a:3", 1.0)]
        group = AveragingGroup(members=members, my_index=0,
                               group_hash=group_hash_of(members))
        payload = compression.compress(
            np.ones((8,), np.float32), compression.NONE)
        frame = _make_frame(sender, _sign_ctx("p", 1, "scatter", "r1"),
                            group.group_hash, 0, 1.0, 8,
                            compression.NONE, payload)
        assert _verify_frame(frame, _sign_ctx("p", 1, "scatter", "r1"),
                             group, 0)
        # replayed to a different receiver: rejected
        assert not _verify_frame(frame, _sign_ctx("p", 1, "scatter", "r2"),
                                 group, 0)

    def test_own_subkey_accepted(self):
        ident = Identity.generate()
        pid = hashlib.sha256(ident.public_bytes).hexdigest()
        marker = b"[owner:" + ident.public_bytes.hex().encode() + b"]"
        assert owner_bound_peer_id(pid.encode() + marker) == pid

    def test_unmarked_subkey_passes_through(self):
        assert owner_bound_peer_id(b"plain-peer-id") == "plain-peer-id"


class TestDataPlaneSigning:
    def test_injected_chunk_ignored(self):
        """A non-member who knows the run id and group layout injects a
        huge-weight chunk into the reduce phase; signed frames mean it is
        dropped and the average matches the honest peers'."""
        nodes = make_swarm(3)
        attacker = nodes[2]
        honest = nodes[:2]
        try:
            tensors = [[np.full((64,), float(i + 1), np.float32)]
                       for i in range(2)]
            groups = {}

            def matchmake(i):
                groups[i] = make_group(honest[i], "sec2", 0, weight=1.0,
                                       matchmaking_time=2.0,
                                       min_group_size=2)

            threads = [threading.Thread(target=matchmake, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            g0 = groups[0]
            assert g0 is not None and g0.size == 2

            # attacker injects poison at every member's scatter tag using
            # the true group hash but its own (non-member) key
            poison = _make_frame(
                attacker.identity, _sign_ctx("sec2", 0, "scatter"),
                g0.group_hash, 0, 1e9, 64, compression.NONE,
                compression.compress(np.full((64,), 1e6, np.float32),
                                     compression.NONE))
            for m in g0.members:
                attacker.send(m.addr, _tag("sec2", 0, "scatter", m.peer_id),
                              poison, timeout=1.0)

            results = {}

            def reduce(i):
                results[i] = run_allreduce(
                    honest[i], groups[i], "sec2", 0, tensors[i], weight=1.0,
                    allreduce_timeout=6.0, sender_timeout=2.0,
                    codec=compression.NONE)

            threads = [threading.Thread(target=reduce, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            want = (flatten_tensors(tensors[0])
                    + flatten_tensors(tensors[1])) / 2
            for i in range(2):
                np.testing.assert_allclose(results[i][0], want, rtol=1e-6)
        finally:
            for n in nodes:
                n.shutdown()


class TestStoreBounds:
    def test_subkey_flood_bounded(self):
        nodes = make_swarm(2)
        try:
            exp = get_dht_time() + 120
            # native cap is 4096 subkeys per key; try to blow past it
            for i in range(4200):
                nodes[1].store("flood", f"s{i:05d}", i, exp)
            got = nodes[0].get("flood") or {}
            assert 0 < len(got) <= 4096
        finally:
            for n in nodes:
                n.shutdown()

    def test_oversized_value_rejected(self):
        nodes = make_swarm(1)
        try:
            ok = nodes[0].store("big", "s", b"x" * (2 << 20),
                                get_dht_time() + 60)
            # local put is bounded too: the record must not be readable
            got = nodes[0].get("big")
            assert got is None
            del ok
        finally:
            nodes[0].shutdown()

class TestPerWriterQuota:
    """One hostile writer must not starve honest announces by filling a
    key's subkey budget (VERDICT r2 weak #5 / next #6): the C++ store caps
    subkeys per OWNER marker inside each key."""

    def test_flooder_capped_but_honest_announce_lands(self):
        from dalle_tpu.swarm.dht import DHT
        node = DHT(rpc_timeout=2.0)
        writer = DHT(rpc_timeout=2.0,
                     initial_peers=[node.visible_address])
        try:
            exp = get_dht_time() + 60
            attacker_owner = "[owner:" + "aa" * 32 + "]"
            for i in range(600):
                writer.store("flood", f"sub{i:05d}{attacker_owner}",
                             {"i": i}, exp)
            # every store (the victim's AND the attacker's own replica)
            # capped this owner at kMaxSubkeysPerOwner=256, far below the
            # 4096 per-key budget...
            got = node.get("flood")
            assert got is not None
            flooded = [k for k in got if k.startswith(b"sub")]
            assert len(flooded) <= 320, len(flooded)
            # ...so an honest writer's announce still lands and reads back
            honest_owner = "[owner:" + "bb" * 32 + "]"
            assert writer.store("flood", f"honest{honest_owner}",
                                {"ok": True}, exp)
            got = node.get("flood")
            assert any(k.startswith(b"honest") for k in got), list(got)[:3]
        finally:
            writer.shutdown()
            node.shutdown()


class TestUnsafePickleGate:
    """utils/torch_io.py (ADVICE r3): a checkpoint the safe weights-only
    loader rejects must fail LOUDLY unless the caller explicitly opts
    into executing its pickle."""

    def _non_tensor_ckpt(self, tmp_path):
        import argparse

        import torch

        path = tmp_path / "wrapped.ckpt"
        # lightning-style wrapper object: rejected by weights_only=True
        torch.save({"state_dict": {}, "hparams": argparse.Namespace(x=1)},
                   str(path))
        return str(path)

    def test_rejected_without_optin(self, tmp_path, monkeypatch):
        import pytest

        from dalle_tpu.utils.torch_io import (UnsafeCheckpointError,
                                              torch_load_trusted)

        monkeypatch.delenv("DALLE_TPU_ALLOW_UNSAFE_PICKLE", raising=False)
        path = self._non_tensor_ckpt(tmp_path)
        with pytest.raises(UnsafeCheckpointError):
            torch_load_trusted(path)

    def test_flag_and_env_optins_load(self, tmp_path, monkeypatch):
        from dalle_tpu.utils.torch_io import torch_load_trusted

        path = self._non_tensor_ckpt(tmp_path)
        assert torch_load_trusted(path, allow_unsafe=True)["hparams"].x == 1
        monkeypatch.setenv("DALLE_TPU_ALLOW_UNSAFE_PICKLE", "1")
        assert torch_load_trusted(path)["hparams"].x == 1

    def test_safe_checkpoints_unaffected(self, tmp_path):
        import torch

        from dalle_tpu.utils.torch_io import torch_load_trusted

        path = tmp_path / "plain.pt"
        torch.save({"w": torch.zeros(2)}, str(path))
        out = torch_load_trusted(str(path))
        assert out["w"].shape == (2,)
