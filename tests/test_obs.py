"""Flight recorder + exposition tests (dalle_tpu/obs, OBSERVABILITY.md).

The contracts pinned here, in order of load-bearing-ness:

- **transparency**: recorder OFF is the uninstrumented path (the
  disabled span is one shared singleton — zero allocation), and
  recorder ON never touches the data: an engine with a tracer emits
  bit-identical codes, an allreduce with the report dict produces
  byte-identical averages.
- **overhead budget**: total recording cost (spans recorded x measured
  per-span cost) stays under a fixed percent of the engine run and of
  a real loopback allreduce round. The budget multiplies two numbers
  measured in the SAME process run, so it holds on a loaded 2-core box
  where wall-vs-wall A/B comparisons flake.
- **the failure-dump path**: a forced oracle failure in a churn-soak
  SUBPROCESS emits SOAK_FLIGHT.json whose last-round spans identify
  the injected fault's peer and phase, plus the always-on merged
  cross-peer timeline artifact.
- **exposition**: /metrics parses as Prometheus text and agrees with
  the /stats ledger (same snapshot source), histograms are cumulative
  and monotone.
- **fetch_metrics edges**: a peer republishing under a new epoch
  supersedes (never double-counts) its prior record; a bound-but-stale
  subkey is dropped, not crashed; pre-r16 records (no proof counters)
  still validate.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from dalle_tpu.config import ServingConfig, tiny_model_config
from dalle_tpu.models.dalle import DALLE, init_params
from dalle_tpu.models.decode import SamplingConfig
from dalle_tpu.obs.exposition import (MetricsRegistry, parse_text,
                                      serving_source, tracer_source)
from dalle_tpu.obs.trace import (NULL_SPAN, Tracer, load_jsonl,
                                 merge_rows, span)
from dalle_tpu.serving.engine import DecodeEngine
from dalle_tpu.serving.server import ServingHTTPServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SAM = SamplingConfig(temperature=1.0, top_k=8)


@pytest.fixture(scope="module")
def flat_setup():
    cfg = tiny_model_config(attn_types=("axial_row", "axial_col"),
                            depth=2)
    params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
    return cfg, params


def _text(cfg, seed=3):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (cfg.text_seq_len,), 2,
        cfg.vocab_text))


# -- tracer core ----------------------------------------------------------

class TestTracer:
    def test_span_records_duration_trace_and_attrs(self):
        t = Tracer(peer="p0")
        with t.span("swarm", "matchmaking", "run:grads:7", group=3) as sp:
            sp.set(extra=1)
        t.event("serving", "submit", "req:9", lane="high")
        rows = t.dump()
        assert [r["phase"] for r in rows] == ["matchmaking", "submit"]
        assert rows[0]["trace"] == "run:grads:7"
        assert rows[0]["dur_s"] >= 0 and rows[0]["a"] == {"group": 3,
                                                          "extra": 1}
        assert rows[1]["dur_s"] == 0.0 and rows[1]["peer"] == "p0"

    def test_span_annotates_error_and_reraises(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("swarm", "allreduce", "r:0"):
                raise ValueError("boom")
        (row,) = t.dump()
        assert row["a"]["error"] == "ValueError"

    def test_disabled_span_is_the_shared_singleton(self):
        """The zero-allocation proof: span(None, ...) returns the SAME
        object every time — the disabled path builds nothing."""
        a = span(None, "swarm", "x", "t", attr=1)
        b = span(None, "serving", "y", "u")
        assert a is NULL_SPAN and b is NULL_SPAN
        with a as sp:
            assert sp.set(anything=1) is NULL_SPAN

    def test_ring_byte_cap_evicts_oldest(self):
        t = Tracer(ring_bytes=2048)
        for i in range(200):
            t.event("swarm", "apply", f"r:{i}")
        rows = t.dump()
        assert t.ring_evictions > 0
        assert len(rows) < 200
        # oldest evicted, newest kept, order preserved
        assert rows[-1]["trace"] == "r:199"
        traces = [int(r["trace"].split(":")[1]) for r in rows]
        assert traces == sorted(traces)

    def test_last_rounds_keeps_n_distinct_traces(self):
        t = Tracer()
        for e in range(6):
            t.event("swarm", "matchmaking", f"r:{e}")
            t.event("swarm", "apply", f"r:{e}")
        last = t.last_rounds(2)
        assert {r["trace"] for r in last} == {"r:4", "r:5"}
        assert len(last) == 4

    def test_jsonl_sink_roundtrip_and_torn_line(self, tmp_path):
        path = str(tmp_path / "p0.jsonl")
        t = Tracer(peer="p0", sink_path=path)
        t.event("swarm", "apply", "r:0", n=1)
        t.event("swarm", "apply", "r:1")
        t.flush()
        with open(path, "a") as fh:
            fh.write('{"torn": ')  # crash mid-append
        rows = load_jsonl(path)
        assert [r["trace"] for r in rows] == ["r:0", "r:1"]
        assert rows[0]["a"] == {"n": 1}

    def test_merge_rows_orders_by_trace_then_peer(self):
        a = [{"peer": "p1", "trace": "r:1", "t0": 5.0, "phase": "x"},
             {"peer": "p1", "trace": "r:0", "t0": 9.0, "phase": "x"}]
        b = [{"peer": "p0", "trace": "r:1", "t0": 2.0, "phase": "x"}]
        merged = merge_rows([a, b])
        assert [(r["trace"], r["peer"]) for r in merged] == [
            ("r:0", "p1"), ("r:1", "p0"), ("r:1", "p1")]

    def test_merge_rows_natural_orders_numeric_epochs(self):
        """Round 10 sorts AFTER round 9 (lexicographic order would put
        run:grads:10 before run:grads:2 and misorder every timeline
        past epoch 9)."""
        rows = [{"peer": "p", "trace": f"run:grads:{e}", "t0": float(e),
                 "phase": "x"} for e in (10, 2, 9, 11, 1)]
        merged = merge_rows([rows])
        assert [r["trace"].rsplit(":", 1)[1] for r in merged] == [
            "1", "2", "9", "10", "11"]

    def test_histogram_is_cumulative_and_monotone(self):
        t = Tracer()
        for d in (0.0005, 0.003, 0.003, 0.2, 40.0):
            t.add("swarm", "allreduce", "r:0", 0.0, d)
        # events are markers, not latencies: they ride the ring but
        # never the phase histograms (trace_report's treatment)
        t.event("swarm", "allreduce", "r:0")
        t.event("serving", "submit", "req:1")
        assert ("serving", "submit") not in t.histogram_snapshot()
        h = t.histogram_snapshot()[("swarm", "allreduce")]
        counts = [c for _le, c in h["buckets"]]
        assert counts == sorted(counts)          # cumulative
        assert h["buckets"][-1] == ("+Inf", 5)   # total in +Inf
        assert h["count"] == 5
        assert abs(h["sum"] - 40.2065) < 1e-6


# -- overhead budget ------------------------------------------------------

def _per_span_cost_s(n: int = 4000) -> float:
    t = Tracer(ring_bytes=64 * 1024)
    t0 = time.perf_counter()
    for i in range(n):
        t.add("serving", "chunk", "engine", 0.0, 0.001, live=2)
    return (time.perf_counter() - t0) / n


class TestOverheadBudget:
    #: recording cost must stay under this fraction of the measured
    #: work it observes (the CI budget the issue pins)
    BUDGET_FRAC = 0.05

    def test_per_span_cost_is_bounded(self):
        # generous absolute ceiling (~100x the typical few-us cost) so
        # the pin survives the 2-core box's scheduling noise
        assert _per_span_cost_s() < 5e-4

    def test_engine_chunk_loop_overhead_within_budget(self, flat_setup):
        """Spans recorded during a real engine run x measured per-span
        cost <= BUDGET_FRAC of the run's wall. Both factors come from
        this process, so the bound is load-independent."""
        cfg, params = flat_setup
        tracer = Tracer(peer="engine")
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=2, steps_per_call=4),
                              sampling=SAM, tracer=tracer).start()
        try:
            t0 = time.perf_counter()
            handles = [engine.submit(_text(cfg, s), jax.random.PRNGKey(s))
                       for s in (11, 12, 13)]
            for h in handles:
                h.result(timeout=300)
            wall = time.perf_counter() - t0
        finally:
            engine.stop()
        assert tracer.spans_recorded > 0
        overhead = tracer.spans_recorded * _per_span_cost_s()
        assert overhead <= self.BUDGET_FRAC * wall, (
            f"recording cost {overhead:.4f}s exceeds "
            f"{self.BUDGET_FRAC:.0%} of the {wall:.3f}s engine run "
            f"({tracer.spans_recorded} spans)")
        # the request timeline actually materialized
        phases = {r["phase"] for r in tracer.dump()}
        assert {"submit", "admit", "first_code", "harvest", "complete",
                "chunk"} <= phases

    def test_allreduce_round_overhead_within_budget(self):
        """Same budget against one real 2-peer loopback round with the
        soak harness's span set around it."""
        from dalle_tpu.swarm import DHT, compression
        from dalle_tpu.swarm.identity import Ed25519PrivateKey, Identity
        from dalle_tpu.swarm.matchmaking import make_group
        from dalle_tpu.swarm.allreduce import run_allreduce
        from dalle_tpu.obs.trace import span as obs_span

        nodes = []
        for i in range(2):
            peers = [nodes[0].visible_address] if nodes else []
            ident = Identity(Ed25519PrivateKey.from_private_bytes(
                bytes([61 + i]) * 32))
            nodes.append(DHT(initial_peers=peers, identity=ident,
                             rpc_timeout=2.0))
        tracers = [Tracer(peer=f"p{i}") for i in range(2)]
        grads = np.arange(2048, dtype=np.float32)
        results = [None, None]
        errors = []

        def peer(i):
            try:
                tr = tracers[i]
                with obs_span(tr, "swarm", "matchmaking", "obs:0"):
                    g = make_group(nodes[i], "obs", epoch=0, weight=1.0,
                                   matchmaking_time=3.0,
                                   min_group_size=2)
                assert g is not None and g.size == 2
                with obs_span(tr, "swarm", "allreduce", "obs:0",
                              group=g.size):
                    out = run_allreduce(
                        nodes[i], g, "obs", 0, [grads], weight=1.0,
                        allreduce_timeout=10.0,
                        codec=compression.UNIFORM8BIT, chunk_elems=512)
                results[i] = out[0]
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=peer, args=(i,))
                   for i in range(2)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
        finally:
            for n in nodes:
                n.shutdown()
        assert not errors, errors
        wall = time.perf_counter() - t0
        np.testing.assert_array_equal(results[0], results[1])
        spans = sum(t.spans_recorded for t in tracers)
        assert spans == 4
        overhead = spans * _per_span_cost_s()
        assert overhead <= self.BUDGET_FRAC * wall, (
            f"{overhead:.5f}s of recording vs {wall:.3f}s round")


# -- transparency ---------------------------------------------------------

class TestTransparency:
    def test_engine_codes_identical_with_and_without_tracer(
            self, flat_setup):
        """Recorder ON observes, never perturbs: same seed, same codes,
        bit for bit — and OFF is the same code path minus the
        `is None` tests, so both sides of the pin hold."""
        cfg, params = flat_setup
        text, key = _text(cfg, 21), jax.random.PRNGKey(77)

        def run(tracer):
            engine = DecodeEngine(
                params, cfg, ServingConfig(n_slots=1, steps_per_call=4),
                sampling=SAM, tracer=tracer).start()
            try:
                return engine.submit(text, key).result(timeout=300)
            finally:
                engine.stop()

        off = run(None)
        on = run(Tracer(peer="e"))
        np.testing.assert_array_equal(off["codes"], on["codes"])

    def test_allreduce_bytes_identical_with_and_without_report(self):
        """The optimizer requests the wire report only when tracing —
        this pins that the report dict is write-only telemetry: averaged
        bytes are identical either way."""
        from dalle_tpu.swarm import DHT, compression
        from dalle_tpu.swarm.identity import Ed25519PrivateKey, Identity
        from dalle_tpu.swarm.matchmaking import make_group
        from dalle_tpu.swarm.allreduce import run_allreduce

        rng = np.random.RandomState(5)
        tensors = [rng.randn(1024).astype(np.float32) for _ in range(2)]

        def round_once(with_report):
            nodes = []
            for i in range(2):
                peers = [nodes[0].visible_address] if nodes else []
                ident = Identity(Ed25519PrivateKey.from_private_bytes(
                    bytes([71 + i]) * 32))
                nodes.append(DHT(initial_peers=peers, identity=ident,
                                 rpc_timeout=2.0))
            results = [None, None]
            errors = []

            def peer(i):
                try:
                    g = make_group(nodes[i], "tp", epoch=0, weight=1.0,
                                   matchmaking_time=3.0,
                                   min_group_size=2)
                    assert g is not None and g.size == 2
                    rep = {} if with_report else None
                    results[i] = run_allreduce(
                        nodes[i], g, "tp", 0, [tensors[i]], weight=1.0,
                        allreduce_timeout=10.0,
                        codec=compression.UNIFORM8BIT, chunk_elems=256,
                        report=rep)[0]
                    if with_report:
                        assert "phases" in rep and rep["complete"]
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)

            threads = [threading.Thread(target=peer, args=(i,))
                       for i in range(2)]
            try:
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=60)
            finally:
                for n in nodes:
                    n.shutdown()
            assert not errors, errors
            return results

        without = round_once(with_report=False)
        with_rep = round_once(with_report=True)
        for a, b in zip(without, with_rep):
            np.testing.assert_array_equal(a, b)


# -- exposition -----------------------------------------------------------

class TestExposition:
    def test_render_escapes_and_types(self):
        reg = MetricsRegistry()
        reg.register("x", lambda: [
            {"name": "dalle_test_ops", "type": "counter",
             "help": "ops", "samples": [("_total", {}, 3)]},
            {"name": "dalle_test_gauge", "type": "gauge",
             "samples": [("", {"k": 'a"b\nc\\d'}, 1.5)]},
        ])
        text = reg.render()
        assert "# TYPE dalle_test_ops counter" in text
        assert "dalle_test_ops_total 3" in text
        assert '{k="a\\"b\\nc\\\\d"}' in text
        parsed = parse_text(text)
        assert parsed["dalle_test_ops_total"][""] == 3.0

    def test_failing_source_degrades_not_500(self):
        reg = MetricsRegistry()
        reg.register("bad", lambda: (_ for _ in ()).throw(
            RuntimeError("dead plane")))
        # malformed FAMILY (missing "samples") must lose only its own
        # source's lines, never the page — the guard covers rendering
        reg.register("malformed", lambda: [
            {"name": "dalle_half", "type": "gauge",
             "samples": [("", {}, 2)]},
            {"name": "dalle_broken", "type": "gauge"}])
        reg.register("good", lambda: [
            {"name": "dalle_ok", "type": "gauge",
             "samples": [("", {}, 1)]}])
        text = reg.render()
        assert "dalle_ok 1" in text
        assert "dalle_half" not in text  # its source failed mid-render

    def test_http_metrics_agrees_with_stats_ledger(self, flat_setup):
        """THE exposition identity: /metrics counters == the /stats
        JSON ledger (one snapshot source), and the text parses as
        Prometheus format including the span histograms."""
        cfg, params = flat_setup
        tracer = Tracer(peer="engine")
        engine = DecodeEngine(params, cfg,
                              ServingConfig(n_slots=1, steps_per_call=4),
                              sampling=SAM, tracer=tracer).start()
        httpd = ServingHTTPServer(("127.0.0.1", 0), engine,
                                  request_timeout_s=300.0)
        thread = threading.Thread(target=httpd.serve_forever,
                                  daemon=True)
        thread.start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            body = json.dumps(
                {"tokens": _text(cfg, 31).tolist(), "seed": 5}).encode()
            req = urllib.request.Request(
                url + "/generate", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                assert resp.status == 200
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=30) as resp:
                assert resp.headers["Content-Type"].startswith(
                    "text/plain")
                metrics = parse_text(resp.read().decode())
            with urllib.request.urlopen(url + "/stats",
                                        timeout=30) as resp:
                stats = json.loads(resp.read())
        finally:
            httpd.shutdown()
            httpd.server_close()
            engine.stop()
            thread.join(timeout=10)
        for key in ("submitted", "admitted", "completed", "cancelled",
                    "failed", "shed"):
            assert metrics[f"dalle_serving_{key}_total"][""] \
                == stats[key], key
        assert stats["submitted"] == stats["completed"] == 1
        # per-lane family carries the lane label
        assert metrics["dalle_serving_lane_completed_total"][
            '{lane="high"}'] == 1.0
        # span-derived histogram rode along (engine had a tracer)
        assert any(k.startswith("dalle_phase_latency_seconds")
                   for k in metrics)
        buckets = metrics["dalle_phase_latency_seconds_bucket"]
        chunk = {k: v for k, v in buckets.items() if 'phase="chunk"' in k}
        assert chunk, buckets
        assert max(chunk.values()) == metrics[
            "dalle_phase_latency_seconds_count"][
                '{phase="chunk",plane="serving"}']


# -- trace_report ---------------------------------------------------------

class TestTraceReport:
    def _rows(self):
        rows = []
        for epoch in range(4):
            for peer, dur in (("p0", 0.1), ("p1", 0.1), ("p2", 0.9)):
                rows.append({"v": 1, "peer": peer, "plane": "swarm",
                             "phase": "allreduce",
                             "trace": f"run:{epoch}",
                             "t0": 100.0 + epoch * 10, "dur_s": dur})
        # one silent gap inside p0's own timeline of run:0
        rows.append({"v": 1, "peer": "p0", "plane": "swarm",
                     "phase": "apply", "trace": "run:0",
                     "t0": 100.1 + 5.0, "dur_s": 0.01})
        return rows

    def test_phase_table_stragglers_and_gaps(self, tmp_path):
        from scripts.trace_report import build_report
        rows = self._rows()
        by_peer = {}
        for r in rows:
            by_peer.setdefault(r["peer"], []).append(r)
        files = []
        for peer, prs in by_peer.items():
            p = tmp_path / f"{peer}.jsonl"
            p.write_text("".join(json.dumps(r) + "\n" for r in prs))
            files.append(str(p))
        rep = build_report(sorted(files), gap_s=1.0, rounds=True)
        assert rep["peers"] == ["p0", "p1", "p2"]
        ph = rep["phases"]["swarm:allreduce"]
        assert ph["n"] == 12 and abs(ph["p50_s"] - 0.1) < 1e-9
        assert ph["max_s"] == 0.9
        # p2 drags EVERY round: straggler attribution names it
        assert rep["stragglers"]["straggles_by_peer"] == {"p2": 4}
        assert rep["stragglers"]["worst"]["peer"] == "p2"
        # the silent window inside p0's run:0 timeline is detected
        assert any(g["peer"] == "p0" and g["trace"] == "run:0"
                   and g["gap_s"] > 1.0 for g in rep["gaps"])
        assert {r["trace"] for r in rep["rounds"]} == {
            f"run:{e}" for e in range(4)}


# -- fetch_metrics aggregation edges (satellite) --------------------------

class _Item:
    def __init__(self, value):
        self.value = value


class _StubDHT:
    """Just enough of the DHT surface for fetch_metrics: a canned
    subkey map + a canned identity binding."""

    peer_id = "me"

    def __init__(self, entries, bound):
        self._entries = entries
        self._bound = bound

    def get(self, key):
        return self._entries

    def bound_peer_id(self, subkey):
        return self._bound.get(subkey)


class TestFetchMetricsEdges:
    def _record(self, peer_id, epoch, **over):
        row = {"peer_id": peer_id, "epoch": epoch,
               "samples_per_second": 8.0, "samples_accumulated": 64,
               "loss": 2.5, "mini_steps": 4}
        row.update(over)
        return row

    def test_republish_under_new_epoch_supersedes(self):
        """One peer, two publishes (epoch 1 then 2) through a REAL DHT
        node: the subkey is the peer id, so the second record replaces
        the first — fetch returns exactly one record at the new epoch
        and the aux aggregate counts ONE alive peer."""
        from dalle_tpu.cli.run_aux_peer import aggregate
        from dalle_tpu.swarm import DHT, Identity
        from dalle_tpu.swarm.metrics import (LocalMetrics, fetch_metrics,
                                             publish_metrics)
        node = DHT(identity=Identity.generate(), rpc_timeout=2.0)
        try:
            for epoch in (1, 2):
                assert publish_metrics(
                    node, "exp",
                    LocalMetrics(**self._record(
                        node.peer_id, epoch, proofs_published=epoch)))
            got = fetch_metrics(node, "exp")
            assert len(got) == 1, "stale epoch-1 record double-counted"
            assert got[0].epoch == 2
            assert got[0].proofs_published == 2
            agg = aggregate(got)
            assert agg["alive_peers"] == 1 and agg["epoch"] == 2
            assert agg["proofs_published"] == 2
        finally:
            node.shutdown()

    def test_bound_but_stale_subkey_dropped_not_crashed(self):
        """Records whose subkey still binds an identity but whose VALUE
        is stale garbage (schema drift, truncated payload, identity
        mismatch) are skipped defensively — never a crash, never a
        forged identity in the aggregate."""
        from dalle_tpu.swarm.metrics import fetch_metrics
        entries = {
            b"good": _Item(self._record("pA", 3)),
            b"malformed": _Item({"epoch": "NaN-garbage"}),
            b"truncated": _Item(None),
            b"mismatch": _Item(self._record("pEvil", 3)),
            b"unbound": _Item(self._record("pB", 3)),
        }
        bound = {b"good": "pA", b"malformed": "pM",
                 b"truncated": "pT", b"mismatch": "pC"}
        got = fetch_metrics(_StubDHT(entries, bound), "exp")
        assert [m.peer_id for m in got] == ["pA"]

    def test_pre_r16_record_without_proof_counters_validates(self):
        from dalle_tpu.swarm.metrics import LocalMetrics
        m = LocalMetrics(**self._record("old", 1))
        assert m.proofs_published == 0
        assert m.proofs_convicted == 0 and m.proofs_rejected == 0

    def test_aggregate_sums_robustness_counters(self):
        from dalle_tpu.cli.run_aux_peer import aggregate
        from dalle_tpu.swarm.metrics import LocalMetrics
        ms = [LocalMetrics(**self._record(
            f"p{i}", 2, proofs_published=i, proofs_convicted=1,
            parts_audited=10)) for i in range(3)]
        agg = aggregate(ms)
        assert agg["proofs_published"] == 3
        assert agg["proofs_convicted"] == 3
        assert agg["parts_audited"] == 30


# -- the failure-dump path (subprocess, the CI satellite) ------------------

class TestFailureDump:
    def test_forced_oracle_failure_emits_flight_dump(self, tmp_path):
        """churn_soak --inject-oracle-failure in a SUBPROCESS: exit 1,
        SOAK_FLIGHT.json's last-round spans identify the injected
        fault's peer and phase, and the merged cross-peer timeline
        artifact exists and is consumable by trace_report."""
        out = tmp_path / "CHURN.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "churn_soak.py"),
             "--peers", "2", "--epochs", "2", "--kills", "0",
             "--joins", "0", "--seed", "5",
             "--matchmaking-time", "0.6", "--allreduce-timeout", "4",
             "--deadline", "90", "--out", str(out),
             "--inject-oracle-failure"],
            cwd=str(tmp_path), env=env, capture_output=True, text=True,
            timeout=180)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        flight = json.loads((tmp_path / "SOAK_FLIGHT.json").read_text())
        assert flight["violations"], "no oracle violation recorded"
        # the last-round spans name the injected fault's peer AND phase
        faults = [r for r in flight["timeline"]
                  if r["phase"] == "fault_injected"]
        assert faults, flight["timeline"]
        assert faults[0]["peer"] == "peer0"
        assert faults[0]["a"]["target_phase"] == "apply"
        assert faults[0]["trace"].endswith(":1")  # the final round
        # the always-on merged timeline artifact, cross-peer
        trace_path = tmp_path / "CHURN_TRACE.jsonl"
        rows = load_jsonl(str(trace_path))
        assert {r["peer"] for r in rows} == {"peer0", "peer1"}
        from scripts.trace_report import build_report
        rep = build_report([str(trace_path)])
        assert "swarm:allreduce" in rep["phases"]
        report = json.loads(out.read_text())
        assert report["pass"] is False
        assert report["artifacts"]["flight"].endswith("SOAK_FLIGHT.json")
        # flight-ring excerpts never bloat the persisted report
        assert all("_spans" not in p for p in report["peers"])


# -- state transfer spans -------------------------------------------------

class TestStateTransferSpans:
    def test_fetch_and_serve_share_the_nonce_trace(self):
        """A state download records a state_fetch span on the client
        and a state_serve span on the server under the SAME
        nonce-derived trace id — the cross-peer correlation needs no
        clock agreement."""
        from dalle_tpu.swarm import DHT, Identity
        from dalle_tpu.swarm.state_transfer import (StateServer,
                                                    load_state_from_peers)
        a = DHT(identity=Identity.generate(), rpc_timeout=2.0)
        b = DHT(initial_peers=[a.visible_address],
                identity=Identity.generate(), rpc_timeout=2.0)
        tr_srv, tr_cli = Tracer(peer="srv"), Tracer(peer="cli")
        state = [np.arange(32, dtype=np.float32)]
        server = StateServer(a, "xfer", lambda: (7, state),
                             announce_period=0.5, tracer=tr_srv).start()
        try:
            result = load_state_from_peers(b, "xfer", timeout=20.0,
                                           tracer=tr_cli)
            assert result is not None and result[0] == 7
            np.testing.assert_array_equal(result[1][0], state[0])
        finally:
            server.stop()
            b.shutdown()
            a.shutdown()
        fetch = [r for r in tr_cli.dump() if r["phase"] == "state_fetch"]
        assert fetch and fetch[-1]["a"]["ok"] is True
        deadline = time.monotonic() + 5.0
        serve = []
        while not serve and time.monotonic() < deadline:
            serve = [r for r in tr_srv.dump()
                     if r["phase"] == "state_serve"]
            time.sleep(0.05)
        assert serve, "server recorded no state_serve span"
        assert serve[-1]["trace"] == fetch[-1]["trace"]
        assert serve[-1]["trace"].startswith("xfer:xfer:")
