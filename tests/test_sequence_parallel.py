"""Sequence/context parallelism: ring + Ulysses attention over the sp axis.

Correctness strategy: the dense masked oracle (models/attention.py
``dense_zoo_attention``) defines the semantics; every sequence-parallel
program must reproduce it on an 8-virtual-device CPU mesh (conftest.py), and
the full model must produce the same loss/grads with sp>1 as on one device.
The reference has no sequence parallelism to cite (SURVEY.md §5 "Absent");
long-context is a first-class extension here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import (ATTN_AXIAL_COL, ATTN_AXIAL_ROW, ATTN_CONV_LIKE,
                              ATTN_FULL, tiny_model_config)
from dalle_tpu.models.attention import dense_zoo_attention
from dalle_tpu.models.dalle import DALLE, init_params
from dalle_tpu.parallel.mesh import make_mesh
from dalle_tpu.parallel.sequence import sp_zoo_attention

TEXT, GRID = 16, 4           # T = 16 + 16 = 32
B, H, D = 4, 4, 8


def _qkv(rng_seed: int = 0):
    rng = np.random.RandomState(rng_seed)
    t = TEXT + GRID * GRID
    shape = (B, t, H, D)
    q, k, v = (jnp.asarray(rng.randn(*shape), jnp.float32) for _ in range(3))
    return q, k, v


def test_ring_matches_dense_full():
    mesh = make_mesh(dp=2, fsdp=1, tp=1, sp=4)
    q, k, v = _qkv()
    want = dense_zoo_attention(q, k, v, ATTN_FULL, TEXT, GRID)
    got = sp_zoo_attention(q, k, v, mesh=mesh, mode="ring",
                           attn_type=ATTN_FULL, text_len=TEXT, grid=GRID)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_tp_axis():
    mesh = make_mesh(dp=1, fsdp=2, tp=2, sp=2)
    q, k, v = _qkv(1)
    want = dense_zoo_attention(q, k, v, ATTN_FULL, TEXT, GRID)
    got = sp_zoo_attention(q, k, v, mesh=mesh, mode="ring",
                           attn_type=ATTN_FULL, text_len=TEXT, grid=GRID)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("attn_type", [ATTN_FULL, ATTN_AXIAL_ROW,
                                       ATTN_AXIAL_COL, ATTN_CONV_LIKE])
def test_ulysses_matches_dense(attn_type):
    mesh = make_mesh(dp=2, fsdp=1, tp=2, sp=2)
    q, k, v = _qkv(2)
    want = dense_zoo_attention(q, k, v, attn_type, TEXT, GRID, conv_kernel=3)
    got = sp_zoo_attention(q, k, v, mesh=mesh, mode="ulysses",
                           attn_type=attn_type, text_len=TEXT, grid=GRID,
                           conv_kernel=3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_rejects_sparse_types():
    mesh = make_mesh(dp=2, fsdp=1, tp=1, sp=4)
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="ring"):
        sp_zoo_attention(q, k, v, mesh=mesh, mode="ring",
                         attn_type=ATTN_AXIAL_ROW, text_len=TEXT, grid=GRID)


def test_ring_config_validation():
    with pytest.raises(ValueError, match="ring"):
        tiny_model_config(sequence_parallel="ring",
                          attn_types=(ATTN_AXIAL_ROW,)).validate()
    tiny_model_config(sequence_parallel="ring").validate()  # full-only: ok


def _batch(cfg, seed=0):
    rng = np.random.RandomState(seed)
    text = jnp.asarray(rng.randint(0, cfg.vocab_text,
                                   (B, cfg.text_seq_len)), jnp.int32)
    image = jnp.asarray(rng.randint(0, cfg.vocab_image,
                                    (B, cfg.image_seq_len)), jnp.int32)
    return text, image


def _loss_and_grads(model, params, text, image):
    def loss_fn(p):
        loss, _ = model.apply(p, text, image)
        return loss
    return jax.jit(jax.value_and_grad(loss_fn))(params)


@pytest.mark.parametrize("mode,attn_types,mesh_axes", [
    ("ring", (ATTN_FULL,), dict(dp=2, fsdp=1, tp=1, sp=4)),
    ("ulysses", (ATTN_AXIAL_ROW, ATTN_AXIAL_COL),
     dict(dp=1, fsdp=2, tp=2, sp=2)),
])
def test_model_loss_and_grads_match_single_device(mode, attn_types,
                                                  mesh_axes):
    """Full model: sp>1 shard_map path == single-device reference numerics,
    through remat and the weight-sharing scan."""
    cfg = tiny_model_config(attn_types=attn_types, sequence_parallel=mode,
                            shared_block_cycle=2, depth=4, remat=True)
    mesh = make_mesh(**mesh_axes)
    model_sp = DALLE(cfg, mesh=mesh)
    model_ref = DALLE(cfg.__class__(**{
        **cfg.__dict__, "sequence_parallel": "none"}))
    params = init_params(model_ref, jax.random.PRNGKey(0))
    text, image = _batch(cfg)

    loss_ref, grads_ref = _loss_and_grads(model_ref, params, text, image)
    loss_sp, grads_sp = _loss_and_grads(model_sp, params, text, image)

    np.testing.assert_allclose(float(loss_sp), float(loss_ref),
                               rtol=1e-5, atol=1e-5)
    flat_ref = jax.tree.leaves(grads_ref)
    flat_sp = jax.tree.leaves(grads_sp)
    for a, b in zip(flat_sp, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
