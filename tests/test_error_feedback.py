"""In-collective quantization (r15): error-feedback residual math
(two-round analytic pin, host/device parity), the wire transparency
contract (EF off + pinned 8-bit == the r14 protocol byte-for-byte),
codec pinning (flapping senders banned), the CollabConfig knob
validation, and the fast convergence A/B (the tier-1 face of
scripts/ef_convergence_ab.py; the wire-mode artifact run is
slow-marked)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.swarm import DHT, Identity, compression
from dalle_tpu.swarm.allreduce import flatten_tensors, run_allreduce
from dalle_tpu.swarm.error_feedback import ErrorFeedback, make_pair
from dalle_tpu.swarm.identity import Ed25519PrivateKey
from dalle_tpu.swarm.matchmaking import make_group

U8 = compression.UNIFORM8BIT
U4 = compression.UNIFORM4BIT


def _roundtrip(x, codec):
    return compression.decompress(compression.compress(x, codec), codec,
                                  x.size)


class TestResidualMath:
    @pytest.mark.parametrize("codec", [U8, U4])
    def test_two_round_carry_analytic(self, codec):
        """The EF-SGD recurrence, pinned over two rounds: after round
        1 the residual is exactly g1 - D(Q(g1)); round 2 compensates
        g2 + r1 before quantizing and stores the new error — so what
        crosses the wire over both rounds sums to (g1 + g2) minus one
        bounded residual, never an accumulating bias."""
        rng = np.random.RandomState(0)
        g1 = (rng.randn(2048) * 0.1).astype(np.float32)
        g2 = (rng.randn(2048) * 0.1).astype(np.float32)
        ef = ErrorFeedback()
        comp1 = ef.compensate(g1.copy())
        np.testing.assert_array_equal(comp1, g1)  # fresh residual is 0
        dec1 = _roundtrip(comp1, codec)
        ef.store(comp1, [dec1])
        r1 = ef.residual_host()
        np.testing.assert_array_equal(r1, g1 - dec1)
        assert np.abs(r1).max() > 0  # real quantization error
        comp2 = ef.compensate(g2.copy())
        np.testing.assert_array_equal(comp2, g2 + r1)
        dec2 = _roundtrip(comp2, codec)
        ef.store(comp2, [dec2])
        np.testing.assert_array_equal(ef.residual_host(),
                                      (g2 + r1) - dec2)
        assert ef.rounds == 2

    def test_device_and_host_residuals_byte_equal(self):
        """The donated device compensate/store produce the same bytes
        as the host numpy path (the flatten-copy contract of
        run_allreduce's device branch depends on it)."""
        rng = np.random.RandomState(1)
        g = (rng.randn(4096) * 0.3).astype(np.float32)
        segs = [slice(0, 1024), slice(1024, 4096)]
        ef_h, ef_d = ErrorFeedback(), ErrorFeedback()
        comp_h = ef_h.compensate(g.copy())
        comp_d = ef_d.compensate(jnp.asarray(g))
        np.testing.assert_array_equal(comp_h, np.asarray(comp_d))
        dec = [_roundtrip(comp_h[s], U8) for s in segs]
        ef_h.store(comp_h, [np.concatenate(dec)])
        ef_d.store(comp_d, [jnp.asarray(d) for d in dec])
        assert ef_h.residual_host().tobytes() == \
            ef_d.residual_host().tobytes()

    def test_consumed_but_unstored_residual_is_counted(self):
        """A round that dies between compensate and store loses its
        residual (safe-but-lossy restart from zero) — the loss must be
        COUNTED, never silent (churny swarms would otherwise shed EF
        every failed round with no trace)."""
        ef = ErrorFeedback()
        g = np.ones(64, np.float32)
        comp = ef.compensate(g.copy())
        dec = comp - np.float32(0.5)
        ef.store(comp, [dec])
        assert ef.lost_rounds == 0 and ef.rounds == 1
        ef.compensate(g.copy())       # round dies here: no store
        ef.compensate(g.copy())       # next round notices the loss
        assert ef.lost_rounds == 1
        # the gather leg's twin: a compensate_slice whose round dies
        # before store_slice is counted on the next carry
        efg = ErrorFeedback()
        part = np.ones(8, np.float32)
        comp = efg.compensate_slice(part, 0, 8, total=16)
        efg.store_slice(comp, comp - np.float32(0.25), 0, 8, total=16)
        assert efg.lost_rounds == 0
        efg.compensate_slice(part, 0, 8, total=16)   # round dies
        efg.compensate_slice(part, 0, 8, total=16)   # counted here
        assert efg.lost_rounds == 1

    def test_slice_api_partial_ownership(self):
        """The gather leg: only the owned slice updates; the rest of
        the residual keeps its pending error across rounds."""
        ef = ErrorFeedback()
        part = np.array([1.5, -2.25, 0.5], np.float32)
        comp = ef.compensate_slice(part, 2, 5, total=8)
        np.testing.assert_array_equal(comp, part)  # fresh = zeros
        dec = part - np.float32(0.125)
        ef.store_slice(comp, dec, 2, 5, total=8)
        r = ef.residual_host()
        np.testing.assert_array_equal(r[2:5], np.float32(0.125))
        np.testing.assert_array_equal(r[[0, 1, 5, 6, 7]], 0.0)
        # a later round owning a DIFFERENT slice leaves [2:5] pending
        comp2 = ef.compensate_slice(np.zeros(3, np.float32), 5, 8,
                                    total=8)
        np.testing.assert_array_equal(comp2, 0.0)
        ef.store_slice(comp2, comp2 + 1.0, 5, 8, total=8)
        r = ef.residual_host()
        np.testing.assert_array_equal(r[2:5], np.float32(0.125))
        np.testing.assert_array_equal(r[5:8], -1.0)


def _loopback(n, base=31):
    nodes = []
    for i in range(n):
        peers = [nodes[0].visible_address] if nodes else []
        ident = Identity(Ed25519PrivateKey.from_private_bytes(
            bytes([base + i]) * 32))
        nodes.append(DHT(initial_peers=peers, identity=ident,
                         rpc_timeout=5.0))
    return nodes


def _round(nodes, prefix, tensors_pp, per_peer_kwargs,
           chunk_elems=1024):
    gs = [None] * len(nodes)
    res = [None] * len(nodes)
    reps = [dict() for _ in nodes]
    errs = []

    def peer(i):
        try:
            gs[i] = make_group(nodes[i], prefix, 0, weight=1.0 + i,
                               matchmaking_time=2.0,
                               min_group_size=len(nodes), encrypt=True)
            assert gs[i] is not None and gs[i].size == len(nodes)
            res[i] = run_allreduce(
                nodes[i], gs[i], prefix, 0, tensors_pp[i],
                weight=1.0 + i, allreduce_timeout=20.0,
                report=reps[i], chunk_elems=chunk_elems,
                **per_peer_kwargs[i])
        except Exception as e:  # noqa: BLE001 - surfaced to the test
            errs.append(repr(e))

    ts = [threading.Thread(target=peer, args=(i,))
          for i in range(len(nodes))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    return res, reps


def _tensors(seed):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=3000).astype(np.float32).reshape(50, 60),
            rng.normal(size=700).astype(np.float32)]


class TestWireIntegration:
    def test_transparency_new_args_inert_when_off(self):
        """EF off + pinned 8-bit must be the r14 protocol byte-for-
        byte: a round called with the r15 argument surface
        (gather_codec explicit, EF None) produces bytes identical to
        the legacy call shape (codec only)."""
        outs = {}
        for tag, kw in (("legacy", dict(codec=U8)),
                        ("r15", dict(codec=U8, gather_codec=U8,
                                     ef_scatter=None, ef_gather=None))):
            nodes = _loopback(2)
            try:
                res, reps = _round(nodes, f"tp_{tag}",
                                   [_tensors(3), _tensors(4)],
                                   [kw, kw])
                assert all(r.get("complete") for r in reps)
                outs[tag] = res
            finally:
                for nd in nodes:
                    nd.shutdown()
        for a, b in zip(outs["legacy"], outs["r15"]):
            for x, y in zip(a, b):
                assert np.asarray(x).tobytes() == np.asarray(y).tobytes()

    def test_ef_round_members_end_byte_identical(self):
        """EF compensation is sender-local; the gather bytes are still
        one broadcast — every member ends the round byte-identical,
        and each peer's residuals come back nonzero (the loop is
        live)."""
        nodes = _loopback(3)
        efs = [make_pair() for _ in range(3)]
        try:
            per = [dict(codec=U8, gather_codec=U4,
                        ef_scatter=efs[i][0], ef_gather=efs[i][1])
                   for i in range(3)]
            res, reps = _round(nodes, "efm",
                               [_tensors(20 + i) for i in range(3)], per)
            assert all(r.get("complete") for r in reps)
            flats = [flatten_tensors(r) for r in res]
            for f in flats[1:]:
                assert flats[0].tobytes() == f.tobytes()
            for sc, ga in efs:
                assert np.abs(sc.residual_host()).max() > 0
                assert np.abs(ga.residual_host()).max() > 0
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_codec_flapping_sender_banned(self):
        """The pinned-codec satellite: a validly-signed sender that
        ships u8 frames into a u4-pinned round is authenticated
        garbage — banned with its weight renormalized out, exactly
        like bad geometry (EF residual scales need ONE codec)."""
        nodes = _loopback(2)
        try:
            per = [dict(codec=U4, pin_codec=True),
                   dict(codec=U8, gather_codec=U4)]  # the flapper
            res, reps = _round(nodes, "flap",
                               [_tensors(1), _tensors(2)], per)
            assert reps[0]["corrupt_senders"] == [nodes[1].peer_id]
            assert not reps[0]["complete"]
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_ef_requires_pinned_block_codec(self):
        nodes = _loopback(2)
        try:
            gs = [None, None]

            def mk(i):
                gs[i] = make_group(nodes[i], "v", 0, weight=1.0,
                                   matchmaking_time=2.0,
                                   min_group_size=2)
            ts = [threading.Thread(target=mk, args=(i,))
                  for i in range(2)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            with pytest.raises(ValueError, match="ef_scatter"):
                run_allreduce(nodes[0], gs[0], "v", 0, _tensors(0),
                              weight=1.0, codec=None,
                              ef_scatter=ErrorFeedback())
            with pytest.raises(ValueError, match="ef_gather"):
                run_allreduce(nodes[0], gs[0], "v", 0, _tensors(0),
                              weight=1.0, codec=U8,
                              gather_codec=compression.FLOAT16,
                              ef_gather=ErrorFeedback())
        finally:
            for nd in nodes:
                nd.shutdown()


class TestConfigKnobs:
    def _mk(self, **over):
        import dataclasses

        from dalle_tpu.config import CollabConfig
        from dalle_tpu.swarm.optimizer import CollaborativeOptimizer

        class _S:
            params = {"w": np.zeros(4, np.float32)}
            opt_state = ()

        cfg = dataclasses.replace(CollabConfig(), **over)

        class _Role:
            swarm_enabled = False
        return CollaborativeOptimizer(None, cfg, _S(), lambda s, g: s,
                                      serve_state=False, role=_Role())

    def test_bits_resolve_and_ef_pair_created(self):
        opt = self._mk(wire_bits_reduce=4, wire_bits_gather=8,
                       ef_residuals=True)
        assert opt._grad_codec == U4
        assert opt._gather_codec == U8
        assert opt._ef_scatter is not None and opt._ef_gather is not None

    def test_defaults_stay_legacy(self):
        opt = self._mk()
        assert opt._grad_codec is None  # size_adaptive dispatch
        assert opt._gather_codec is None
        assert opt._ef_scatter is None and opt._ef_gather is None

    def test_validation(self):
        with pytest.raises(ValueError, match="wire_bits"):
            self._mk(wire_bits_reduce=16)
        with pytest.raises(ValueError, match="ef_residuals"):
            self._mk(ef_residuals=True, wire_bits_reduce=8)
        with pytest.raises(ValueError, match="power_sgd"):
            self._mk(grad_compression="power_sgd", wire_bits_reduce=8)


class TestConvergenceAB:
    def test_fast_sim_ab(self):
        """Tier-1 face of scripts/ef_convergence_ab.py: the in-process
        butterfly simulation over a short horizon. u4+EF must track
        fp32 within tolerance AND beat u4-without-EF (the stress
        problem is built so naive u4 visibly stalls)."""
        from scripts.ef_convergence_ab import run_ab
        report = run_ab(epochs=12, dim=2048, rows_per_peer=48,
                        tolerance=0.10,
                        configs=["fp32", "u4", "u4+ef"])
        assert report["pass"], report["violations"]
        t = report["trajectories"]
        assert t["u4+ef"]["final_loss"] < t["u4"]["final_loss"]

    @pytest.mark.slow
    def test_wire_ab_matches_artifact(self):
        """The artifact run (EF_CONVERGENCE_AB.json): the same A/B
        through real loopback DHT rounds, all five configs."""
        from scripts.ef_convergence_ab import run_ab
        report = run_ab(wire=True, epochs=24, tag="t")
        assert report["pass"], report["violations"]
