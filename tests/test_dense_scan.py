"""dense_scan: the cycle=0 (no weight sharing) stack as an nn.scan with
STACKED per-iteration params (transformer.py). The unrolled dense tree and
the scanned dense tree must express the SAME model: slicing each scan
repetition out of the stacked leaves reproduces the unrolled layers
(which is also how models/decode.py::layer_params reads the scanned tree).
"""

import jax
import jax.numpy as jnp
import numpy as np

from dalle_tpu.config import flagship_model_config
from dalle_tpu.models.dalle import DALLE, init_params


def _cfg(dense_scan, depth=9):
    return flagship_model_config(
        depth=depth, dim=64, heads=2, head_dim=32, text_seq_len=8,
        image_grid=4, vocab_text=32, vocab_image=32, head_chunk=0,
        shared_block_cycle=0, remat_skip_blocks=0, scan_unroll=2,
        # f32 so scanned-vs-unrolled parity is EXACT (measured 0.0 diff);
        # under bf16 the two reduction orders drift like any reordering
        dense_scan=dense_scan, dtype="float32")


def _unrolled_from_scanned(params, cfg):
    """Slice the stacked cycle/block_{sub} leaves into block_{uid} entries
    of the unrolled tree (same mapping as decode.layer_params)."""
    import copy
    group = len(cfg.attn_types)
    tr = params["params"]["transformer"]
    out_tr = {k: v for k, v in tr.items() if k != "cycle"}
    body = cfg.depth - (1 if cfg.final_conv_block else 0)
    for uid in range(body):
        rep, sub = divmod(uid, group)
        out_tr[f"block_{uid}"] = jax.tree.map(
            lambda a: a[rep], tr["cycle"][f"block_{sub}"])
    out = copy.copy(params)
    out["params"] = dict(params["params"], transformer=out_tr)
    return out


class TestDenseScan:
    def test_scanned_tree_shape(self):
        cfg = _cfg(True)
        params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
        tr = params["params"]["transformer"]
        assert "cycle" in tr and "block_wconv" in tr
        # 8 body layers / group 4 = 2 reps, stacked leading axis
        k = tr["cycle"]["block_0"]["attn"]["q"]["kernel"]
        assert k.shape == (2, cfg.dim, cfg.dim)
        # no unrolled body blocks alongside the scan
        assert not any(k.startswith("block_") and k != "block_wconv"
                       for k in tr)

    def test_scanned_matches_unrolled_forward_and_grads(self):
        cfg_s, cfg_u = _cfg(True), _cfg(False)
        model_s, model_u = DALLE(cfg_s), DALLE(cfg_u)
        params_s = init_params(model_s, jax.random.PRNGKey(0))
        params_u = _unrolled_from_scanned(params_s, cfg_s)
        text = jnp.zeros((2, cfg_s.text_seq_len), jnp.int32)
        image = jnp.ones((2, cfg_s.image_seq_len), jnp.int32)

        l_s = float(model_s.apply(params_s, text, image)[0])
        l_u = float(model_u.apply(params_u, text, image)[0])
        assert abs(l_s - l_u) / abs(l_u) < 1e-6, (l_s, l_u)

        g_s = jax.grad(lambda p: model_s.apply(p, text, image)[0])(params_s)
        g_u = jax.grad(lambda p: model_u.apply(p, text, image)[0])(params_u)
        # compare per-layer: slice the scanned grads like the params
        g_su = _unrolled_from_scanned(g_s, cfg_s)
        flat_u, _ = jax.tree_util.tree_flatten_with_path(g_u["params"])
        flat_s = dict(jax.tree_util.tree_flatten_with_path(
            g_su["params"])[0])
        for path, a in flat_u:
            b = flat_s[path]
            # atol 5e-6, not 1e-6: the scanned model's backward pass
            # accumulates the embedding-grad carry in scan order while
            # the unrolled model sums per-layer contributions — two f32
            # reduction orders. Seed repro (this box, jax 0.4.37 CPU):
            # 1/8192 token_emb elements off by 1.07e-6 absolute
            # (3e-4 relative on a ~3.5e-3 element) — reassociation
            # noise, orders of magnitude below any real wiring bug,
            # which this test catches at O(1e-1).
            np.testing.assert_allclose(
                np.asarray(b, np.float32), np.asarray(a, np.float32),
                rtol=1e-5, atol=5e-6,
                err_msg=jax.tree_util.keystr(path))

    def test_overhang_discarded(self):
        # depth 10 -> body 9 = 2 reps x 4 + 1: the 3 overhanging block
        # applications of rep 2 must not change the loss, and their param
        # slices must get ZERO grads
        cfg = _cfg(True, depth=10)
        model = DALLE(cfg)
        params = init_params(model, jax.random.PRNGKey(0))
        text = jnp.zeros((1, cfg.text_seq_len), jnp.int32)
        image = jnp.ones((1, cfg.image_seq_len), jnp.int32)
        g = jax.grad(lambda p: model.apply(p, text, image)[0])(params)
        tr = g["params"]["transformer"]["cycle"]
        # rep 2 exists for block_1..block_3 only as overhang
        for sub in (1, 2, 3):
            leaf = tr[f"block_{sub}"]["attn"]["q"]["kernel"]
            assert leaf.shape[0] == 3
            assert float(jnp.abs(leaf[2]).max()) == 0.0, sub
        # the real slot of rep 2 (block_0 -> layer 8) has signal
        assert float(jnp.abs(tr["block_0"]["attn"]["q"]["kernel"][2]).max()) > 0

    def test_shallow_dense_scan_unrolls_and_decodes(self):
        # body depth <= group: no scan happens (reps 1), the tree stores
        # plain block_{uid} params, and layer_params must NOT try to
        # slice a stacked axis (dense_scan_reps() is the shared guard)
        from dalle_tpu.models.decode import layer_params
        cfg = _cfg(True, depth=4)
        assert cfg.dense_scan_reps() == 0
        params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
        tr = params["params"]["transformer"]
        assert "cycle" not in tr and "block_0" in tr
        layers = layer_params(params, cfg)
        assert len(layers) == cfg.depth
        assert layers[0]["attn"]["q"]["kernel"].ndim == 2

    def test_stacked_kernels_shard_like_unrolled(self):
        # the sharding rules were written for rank-2 kernels; the stacked
        # rank-3 leaves must shift fsdp/tp onto the SAME matmul dims
        # (reps unsharded), not onto (reps, contraction)
        from jax.sharding import PartitionSpec as P

        from dalle_tpu.parallel.sharding import param_specs
        cfg = _cfg(True)
        params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
        specs = param_specs(params)
        tr = specs["params"]["transformer"]
        assert tr["cycle"]["block_0"]["attn"]["q"]["kernel"] == P(
            None, "fsdp", "tp")
        assert tr["cycle"]["block_0"]["ff"]["wo"]["kernel"] == P(
            None, "tp", "fsdp")
        # unstacked w_conv keeps the plain rank-2 layout
        assert tr["block_wconv"]["attn"]["q"]["kernel"] == P("fsdp", "tp")

    def test_lamb_trust_ratio_matches_unrolled(self):
        # LAMB computes trust ratios per tensor; for stacked leaves that
        # must mean PER SLICE, or the stacked model would optimize
        # differently from the unrolled model it re-stages
        from dalle_tpu.config import OptimizerConfig
        from dalle_tpu.optim import make_optimizer

        cfg_s, cfg_u = _cfg(True), _cfg(False)
        model_s, model_u = DALLE(cfg_s), DALLE(cfg_u)
        params_s = init_params(model_s, jax.random.PRNGKey(0))
        params_u = _unrolled_from_scanned(params_s, cfg_s)
        text = jnp.zeros((2, cfg_s.text_seq_len), jnp.int32)
        image = jnp.ones((2, cfg_s.image_seq_len), jnp.int32)
        g_s = jax.grad(lambda p: model_s.apply(p, text, image)[0])(params_s)
        g_u = jax.grad(lambda p: model_u.apply(p, text, image)[0])(params_u)

        tx = make_optimizer(OptimizerConfig(state_bits=32, warmup_steps=2,
                                            total_steps=100))
        upd_s, _ = tx.update(g_s, tx.init(params_s), params_s)
        upd_u, _ = tx.update(g_u, tx.init(params_u), params_u)
        upd_su = _unrolled_from_scanned(upd_s, cfg_s)
        flat_u = jax.tree_util.tree_flatten_with_path(upd_u["params"])[0]
        flat_s = dict(jax.tree_util.tree_flatten_with_path(
            upd_su["params"])[0])
        for path, a in flat_u:
            np.testing.assert_allclose(
                np.asarray(flat_s[path], np.float32),
                np.asarray(a, np.float32), rtol=1e-5, atol=1e-7,
                err_msg=jax.tree_util.keystr(path))

    def test_decode_layer_params_slices_scanned_tree(self):
        from dalle_tpu.models.decode import layer_params
        cfg = _cfg(True)
        params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
        layers = layer_params(params, cfg)
        assert len(layers) == cfg.depth
        group = len(cfg.attn_types)
        tr = params["params"]["transformer"]
        for uid in (0, 5, 7):
            rep, sub = divmod(uid, group)
            want = tr["cycle"][f"block_{sub}"]["attn"]["q"]["kernel"][rep]
            got = layers[uid]["attn"]["q"]["kernel"]
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))
        assert layers[-1]["attn_type"] == "conv_like"
