"""Thread lifecycle regression tests: every long-lived background
thread in the tree must be a daemon (so a missed join can never block
interpreter exit) AND terminate within its bounded stop/close path (so
shutdown is deterministic, not process-exit roulette).

This is the runtime face of graftlint's `thread-daemon-join` rule
(LINTS.md): the rule proves the discipline statically at the spawn
sites; these tests prove the stop paths actually reap the threads. The
clean-exit assertion is the daemon check — a non-daemon thread that
outlives its owner is exactly the thing that wedges `python -m pytest`
and real trainer shutdowns.

Everything here runs against stubs (no native DHT, no network, no
model): thread mechanics only, milliseconds per test.
"""

import threading
import time

from dalle_tpu.swarm.rendezvous import RendezvousAdvertiser
from dalle_tpu.swarm.state_transfer import StateServer
from dalle_tpu.training.checkpoint import _AsyncWriter
from dalle_tpu.training.remote_sink import RemoteSink, UploadWorker


def _wait_dead(thread, timeout=5.0):
    thread.join(timeout=timeout)
    return not thread.is_alive()


class _StubDHT:
    """The slice of the DHT surface the advertiser/state-server threads
    touch, with no native node behind it."""

    peer_id = "stub-peer"
    reachable_address = ""       # pull-only: advertise() is a no-op
    visible_address = ""

    def store(self, *a, **k):
        return True

    def recv(self, tag, timeout=0.5):
        time.sleep(min(0.01, timeout))
        return None


class TestAsyncCheckpointWriter:
    def test_daemon_and_reaped_on_close(self):
        w = _AsyncWriter()
        assert w._thread.daemon, "ckpt writer must not block exit"
        done = threading.Event()
        w.submit("ckpt", done.set, "ckpt_1")
        w.close(flush_timeout=10.0)
        assert done.is_set(), "queued write must land before close"
        assert _wait_dead(w._thread), "close() must reap the writer"

    def test_close_without_work(self):
        w = _AsyncWriter()
        w.close(flush_timeout=5.0)
        assert _wait_dead(w._thread)


class TestUploadWorker:
    def test_daemon_and_reaped_on_close(self):
        uploads = []

        class Sink(RemoteSink):
            def upload(self, path):
                uploads.append(path)
                return True

        worker = UploadWorker(Sink(), "stub://dest")
        assert worker._thread.daemon
        worker.submit("a-checkpoint")
        worker.close(timeout=10.0)
        assert _wait_dead(worker._thread), "close() must reap the worker"
        assert uploads == ["a-checkpoint"], \
            "the pending upload must drain before shutdown"


class TestRendezvousAdvertiser:
    def test_stop_joins_bounded(self):
        adv = RendezvousAdvertiser(_StubDHT(), "test-prefix", ttl=0.5)
        assert adv.daemon, "advertiser must not block exit"
        adv.start()
        time.sleep(0.05)
        t0 = time.monotonic()
        adv.stop(join_timeout=10.0)     # stop() now signals AND joins
        assert not adv.is_alive(), "stop() must reap the advertiser"
        assert time.monotonic() - t0 < 5.0, "join must not wait a ttl"

    def test_stop_before_start_is_safe(self):
        adv = RendezvousAdvertiser(_StubDHT(), "test-prefix")
        adv.stop()                      # never started: no join, no raise


class _StubEngine:
    """The readiness surface ServingAdvertiser publishes."""

    tracer = None

    def readiness(self):
        return {"queue_depth": 0, "queue_depth_by_lane": {},
                "queue_capacity": 1, "live_slots": 0, "n_slots": 1,
                "max_live": 1, "occupancy": 0.0, "service_ema_s": None,
                "brownout": False, "draining": False, "shed": 0,
                "browned": 0, "cancelled_mid_decode": 0,
                "goodput_img_per_s": 0.0, "prefix_hits": 0,
                "prefix_misses": 0}


class TestServingAdvertiser:
    """serving/router.py's advertiser follows the RendezvousAdvertiser
    discipline: daemonized, stop() signals AND bounded-joins (an
    in-flight publish against a torn-down native DHT node is a
    use-after-free)."""

    def test_stop_joins_bounded(self):
        from dalle_tpu.serving.router import ServingAdvertiser
        adv = ServingAdvertiser(_StubDHT(), "t", _StubEngine(),
                                "http://u", ttl=0.5)
        assert adv.daemon, "advertiser must not block exit"
        adv.start()
        time.sleep(0.05)
        t0 = time.monotonic()
        adv.stop(join_timeout=10.0)
        assert not adv.is_alive(), "stop() must reap the advertiser"
        assert time.monotonic() - t0 < 5.0, "join must not wait a ttl"

    def test_stop_before_start_is_safe(self):
        from dalle_tpu.serving.router import ServingAdvertiser
        ServingAdvertiser(_StubDHT(), "t", _StubEngine(),
                          "http://u").stop()


class TestRouterRefresher:
    def test_stop_joins_bounded(self):
        from dalle_tpu.serving.router import Router
        router = Router(lambda: {}, refresh_s=0.2).start()
        time.sleep(0.05)
        t0 = time.monotonic()
        router.stop(join_timeout=10.0)
        assert not router._thread.is_alive(), \
            "stop() must reap the refresher"
        assert time.monotonic() - t0 < 5.0

    def test_stop_before_start_is_safe(self):
        from dalle_tpu.serving.router import Router
        Router(lambda: {}).stop()       # never started: no join, no raise


class TestStateServer:
    def test_stop_joins_bounded(self):
        server = StateServer(_StubDHT(), "test-prefix",
                             provider=lambda: (0, []),
                             announce_period=0.2)
        assert server._thread.daemon, "state server must not block exit"
        server.start()
        time.sleep(0.05)
        server.stop()
        assert _wait_dead(server._thread), "stop() must reap the server"


def test_no_stray_nondaemon_threads_after_shutdown():
    """The clean-interpreter-exit regression: spin up every owned
    background worker, shut them all down, and require (a) every thread
    they spawned is gone and (b) nothing non-daemon remains beyond the
    threads that predate the test — a forgotten non-daemon worker here
    is precisely what turns `python -c 'train(); exit()'` into a hang.
    """
    before = set(threading.enumerate())

    writer = _AsyncWriter()

    class NullSink(RemoteSink):
        def upload(self, path):
            return True

    worker = UploadWorker(NullSink(), "stub://dest")
    adv = RendezvousAdvertiser(_StubDHT(), "exit-test", ttl=0.5)
    adv.start()
    server = StateServer(_StubDHT(), "exit-test",
                         provider=lambda: (0, []), announce_period=0.2)
    server.start()

    spawned = [t for t in threading.enumerate() if t not in before]
    assert spawned, "expected live background threads"
    assert all(t.daemon for t in spawned), (
        "non-daemon background thread(s) would block interpreter exit: "
        f"{[t.name for t in spawned if not t.daemon]}")

    writer.close(flush_timeout=5.0)
    worker.close(timeout=5.0)
    adv.stop(join_timeout=5.0)
    server.stop()

    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline \
            and any(t.is_alive() for t in spawned):
        time.sleep(0.02)
    leaked = [t.name for t in spawned if t.is_alive()]
    assert not leaked, f"threads outlived their stop paths: {leaked}"
