"""Verified aggregation (swarm/audit.py): the challenge function, the
transcript plane (sign/chunk/post/fetch/strict-open), the replay's
rejection taxonomy, replay determinism (sequential, repeated, and
--jobs-parallel — the drop-set is a pure function of the transcript),
byte-transparency of audit-off AND audit-on honest rounds, live-socket
conviction of wrong-part and omitting owners, the audit worker's
lifecycle, and the hostile-owner soak gate (fast variant tier-1, full
slow-marked).
"""

import concurrent.futures
import hashlib
import json
import threading
import time

import numpy as np
import pytest

from dalle_tpu.swarm import DHT, Identity, compression
from dalle_tpu.swarm.allreduce import (_part_slices, flatten_tensors,
                                       run_allreduce)
from dalle_tpu.swarm.audit import (AUDIT_FAIL_REASON, AUDIT_OMIT_REASON,
                                   AUDIT_TIMEOUT_REASON, AuditPolicy,
                                   AuditWorker, RoundAudit, _audit_ctx,
                                   _audit_tag, audit_round,
                                   challenged_parts, fetch_transcript,
                                   open_transcript, replay_transcript)
from dalle_tpu.swarm.chaos import ByzantineOp, ChaosDHT, FaultPlan
from dalle_tpu.swarm.health import (GOSSIP_REASONS, STRIKE_WEIGHTS,
                                    PeerHealthLedger)
from dalle_tpu.swarm.identity import Ed25519PrivateKey, signed_frame
from dalle_tpu.swarm.matchmaking import make_group
from dalle_tpu.swarm.screening import GradientScreen, ScreenPolicy


# -- the challenge ---------------------------------------------------------

class TestChallenge:
    def test_frac_bounds(self):
        assert challenged_parts("p", 0, 5, 1.0) == {0, 1, 2, 3, 4}
        assert challenged_parts("p", 0, 5, 0.0) == set()
        assert challenged_parts("p", 0, 0, 1.0) == set()

    def test_deterministic_and_round_varying(self):
        a = challenged_parts("p", 3, 64, 0.25)
        b = challenged_parts("p", 3, 64, 0.25)
        assert a == b  # every member derives the identical set
        assert challenged_parts("p", 4, 64, 0.25) != a \
            or challenged_parts("q", 3, 64, 0.25) != a
        # the sample tracks the probability loosely
        assert 4 <= len(a) <= 32

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            AuditPolicy(frac=1.5)
        with pytest.raises(ValueError):
            AuditPolicy(ttl=0)
        with pytest.raises(ValueError):
            AuditPolicy(fetch_retries=0)
        with pytest.raises(ValueError):
            AuditPolicy(chunk_bytes=16)

    def test_new_strike_reasons_registered(self):
        assert STRIKE_WEIGHTS[AUDIT_FAIL_REASON] == 2.0
        assert STRIKE_WEIGHTS[AUDIT_OMIT_REASON] == 2.0
        assert STRIKE_WEIGHTS[AUDIT_TIMEOUT_REASON] == 1.0
        # only the replay verdict gossips: omission is victim-only
        # knowledge, silence is unattributable
        assert AUDIT_FAIL_REASON in GOSSIP_REASONS
        assert AUDIT_OMIT_REASON not in GOSSIP_REASONS
        assert AUDIT_TIMEOUT_REASON not in GOSSIP_REASONS


# -- live-socket harness ---------------------------------------------------

def _det_swarm(n, base=61):
    nodes = []
    for i in range(n):
        peers = [nodes[0].visible_address] if nodes else []
        ident = Identity(Ed25519PrivateKey.from_private_bytes(
            bytes([base + i]) * 32))
        nodes.append(DHT(initial_peers=peers, identity=ident,
                         rpc_timeout=2.0))
    return nodes


def _run_threads(fns, timeout=60):
    results = [None] * len(fns)
    errors = []

    def wrap(i, fn):
        try:
            results[i] = fn()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i, fn))
               for i, fn in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    if errors:
        raise errors[0]
    return results


def _audited_round(nodes, prefix, tensors, *, dhts=None, screen=None,
                   policy=None, mpw=100.0, codec=compression.NONE,
                   audit_on=True, chunk_elems=None, gather_codec=None,
                   efs=None, epoch=0):
    """One full-group round with per-peer RoundAudits armed; returns
    (results[(group, out)], ras, ledgers). ``efs`` (optional
    per-peer (scatter, gather) ErrorFeedback pairs) and
    ``gather_codec`` arm the r15 quantized-wire legs."""
    from dalle_tpu.swarm.allreduce import CHUNK_ELEMS
    n = len(nodes)
    dhts = dhts or list(nodes)
    policy = policy or AuditPolicy(frac=1.0, fetch_timeout=2.0)
    screen = screen or GradientScreen(ScreenPolicy())
    ledgers = [PeerHealthLedger() for _ in range(n)]
    ras = [RoundAudit(prefix, epoch, policy) if audit_on else None
           for _ in range(n)]

    def peer(i):
        g = make_group(dhts[i], prefix, epoch=epoch, weight=1.0,
                       matchmaking_time=2.0, min_group_size=n)
        assert g is not None and g.size == n
        ef_kw = {} if efs is None else dict(ef_scatter=efs[i][0],
                                            ef_gather=efs[i][1])
        return g, run_allreduce(
            dhts[i], g, prefix, epoch, tensors[i], weight=1.0,
            allreduce_timeout=8.0, sender_timeout=1.5, codec=codec,
            ledger=ledgers[i], screen=screen, max_peer_weight=mpw,
            audit=ras[i], gather_codec=gather_codec,
            chunk_elems=chunk_elems or CHUNK_ELEMS, **ef_kw)

    results = _run_threads([lambda i=i: peer(i) for i in range(n)])
    return results, ras, ledgers


def _int_tensors(n, size=400, seed=5):
    rng = np.random.RandomState(seed)
    base = rng.randint(-8, 9, size=size).astype(np.float32)
    return [[base + i] for i in range(n)]


# -- transcript plane ------------------------------------------------------

class TestTranscript:
    @pytest.fixture(scope="class")
    def round5(self):
        nodes = _det_swarm(5)
        try:
            screen = GradientScreen(ScreenPolicy())
            results, ras, ledgers = _audited_round(
                nodes, "tr", _int_tensors(5), screen=screen)
            yield nodes, results, ras, ledgers, screen
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_signed_roundtrip_and_binding(self, round5):
        nodes, results, ras, _led, _screen = round5
        owner_i = next(i for i in range(5)
                       if ras[i].audits_mine and ras[i].posted)
        ra = ras[owner_i]
        blob = ra.build_transcript(nodes[owner_i].identity)
        tr = open_transcript(blob, "tr", 0, ra.my_part,
                             nodes[owner_i].peer_id)
        assert tr is not None
        assert set(tr["order"]) | {ra.group.my_index} >= set(tr["order"])
        # wrong epoch / part / owner: the binding rejects
        assert open_transcript(blob, "tr", 1, ra.my_part,
                               nodes[owner_i].peer_id) is None
        assert open_transcript(blob, "tr", 0, ra.my_part + 1,
                               nodes[owner_i].peer_id) is None
        other = nodes[(owner_i + 1) % 5].peer_id
        assert open_transcript(blob, "tr", 0, ra.my_part, other) is None
        # a flipped byte anywhere kills the signature
        flipped = bytearray(blob)
        flipped[len(flipped) // 2] ^= 1
        assert open_transcript(bytes(flipped), "tr", 0, ra.my_part,
                               nodes[owner_i].peer_id) is None

    def test_fetch_reassembles_chunked_posts(self, round5):
        nodes, results, ras, _led, _screen = round5
        owner_i = next(i for i in range(5) if ras[i].audits_mine)
        ra = ras[owner_i]
        # small chunk_bytes forces multi-chunk posting
        small = AuditPolicy(frac=1.0, chunk_bytes=1024,
                            fetch_timeout=2.0)
        ra2 = RoundAudit("tr2", 0, small)
        ra2.__dict__.update({k: v for k, v in ra.__dict__.items()
                             if k not in ("prefix", "policy")})
        ra2.prefix, ra2.policy = "tr2", small
        assert ra2.post_transcript(nodes[owner_i])
        got = fetch_transcript(
            nodes[(owner_i + 1) % 5], ra.owners[ra.my_part].addr,
            "tr2", 0, ra.my_part, small, group_key=ra.group.group_key)
        assert got == ra2.build_transcript(nodes[owner_i].identity)
        assert open_transcript(got, "tr2", 0, ra.my_part,
                               nodes[owner_i].peer_id) is not None

    def test_unknown_payload_keys_rejected(self, round5):
        import msgpack
        nodes, _res, ras, _led, _screen = round5
        owner_i = next(i for i in range(5) if ras[i].audits_mine)
        ra = ras[owner_i]
        blob = ra.build_transcript(nodes[owner_i].identity)
        tr = open_transcript(blob, "tr", 0, ra.my_part,
                             nodes[owner_i].peer_id)
        payload = msgpack.packb({
            "v": 1, "epoch": 0, "part": ra.my_part, "init": tr["init"],
            "order": tr["order"], "drops": {}, "evidence": {},
            "frames": {}, "extra": 1}, use_bin_type=True)
        forged = signed_frame(nodes[owner_i].identity,
                              _audit_ctx("tr", 0, ra.my_part), b"",
                              payload)
        assert open_transcript(forged, "tr", 0, ra.my_part,
                               nodes[owner_i].peer_id) is None


# -- replay: honest pass + rejection taxonomy ------------------------------

def _replay_kwargs(ra, screen, mpw=100.0):
    return dict(group=ra.group, prefix=ra.prefix, epoch=ra.epoch,
                part=ra.my_part, part_elems=ra.part_sizes[ra.my_part],
                chunk_elems=ra.chunk_elems, codec=ra.codec,
                adaptive_threshold=ra.adaptive_threshold, screen=screen,
                max_peer_weight=mpw)


def _mutated(nodes, ra, mutate):
    """Open the owner's own transcript, apply ``mutate(tr_dict)``, and
    re-sign with the owner's REAL identity — exactly what a lying
    owner can do."""
    import msgpack
    owner_ident = next(nd.identity for nd in nodes
                       if nd.peer_id == ra.owners[ra.my_part].peer_id)
    blob = ra.build_transcript(owner_ident)
    tr = open_transcript(blob, ra.prefix, ra.epoch, ra.my_part,
                         ra.owners[ra.my_part].peer_id)
    raw = {"v": 1, "epoch": ra.epoch, "part": ra.my_part,
           "init": tr["init"], "order": list(tr["order"]),
           "drops": {str(k): v for k, v in tr["drops"].items()},
           "evidence": {str(k): v for k, v in tr["evidence"].items()},
           "frames": {str(k): v for k, v in tr["frames"].items()}}
    mutate(raw)
    payload = msgpack.packb(raw, use_bin_type=True)
    forged = signed_frame(owner_ident,
                          _audit_ctx(ra.prefix, ra.epoch, ra.my_part),
                          b"", payload)
    return open_transcript(forged, ra.prefix, ra.epoch, ra.my_part,
                           ra.owners[ra.my_part].peer_id)


class TestReplay:
    @pytest.fixture(scope="class")
    def round5(self):
        nodes = _det_swarm(5, base=71)
        try:
            screen = GradientScreen(ScreenPolicy())
            results, ras, ledgers = _audited_round(
                nodes, "rp", _int_tensors(5, seed=9), screen=screen)
            yield nodes, results, ras, ledgers, screen
        finally:
            for nd in nodes:
                nd.shutdown()

    def _owner_ra(self, ras):
        return next(ra for ra in ras if ra.audits_mine)

    def test_honest_transcript_replays_bit_exact(self, round5):
        nodes, results, ras, _led, screen = round5
        ra = self._owner_ra(ras)
        tr = _mutated(nodes, ra, lambda raw: None)
        res = replay_transcript(tr, **_replay_kwargs(ra, screen))
        assert res.ok, res.why
        # every member's gathered bytes for this part match the replay
        for other in ras:
            if other is ra:
                continue
            assert ra.my_part in other.gathered
            assert res.values.tobytes() \
                == other.gathered[ra.my_part].tobytes()

    def test_replay_matches_analytic_average(self, round5):
        nodes, results, ras, _led, screen = round5
        ra = self._owner_ra(ras)
        tr = _mutated(nodes, ra, lambda raw: None)
        res = replay_transcript(tr, **_replay_kwargs(ra, screen))
        flats = [flatten_tensors(t) for t in _int_tensors(5, seed=9)]
        lo, hi = _part_slices(flats[0].size, 5)[ra.my_part]
        want = sum(f[lo:hi] for f in flats) / 5.0
        np.testing.assert_array_equal(res.values, want)

    def test_lying_mutations_rejected(self, round5):
        nodes, _res, ras, _led, screen = round5
        ra = self._owner_ra(ras)
        kw = _replay_kwargs(ra, screen)

        def why(mutate):
            tr = _mutated(nodes, ra, mutate)
            assert tr is not None
            res = replay_transcript(tr, **kw)
            assert not res.ok
            return res.why

        # a duplicate application inflates one sender's influence
        assert why(lambda r: r["order"].append(r["order"][0])) \
            == "duplicate-sender-in-order"
        # claiming an applied sender was ALSO dropped is incoherent
        assert why(lambda r: r["drops"].update(
            {str(r["order"][0]): "screen-outlier"})) \
            == "sender-both-applied-and-dropped"
        # a provable drop (corrupt-chunk) with no offending frame as
        # evidence would let an owner censor anyone with cover
        def fake_corrupt(r):
            s = r["order"].pop()
            r["frames"].pop(str(s), None)
            r["drops"][str(s)] = "corrupt-chunk"
        assert why(fake_corrupt) == "unevidenced-corrupt-drop"
        # claiming an honest sender as a screen outlier fails the
        # screen REPLAY (the deterministic f64 verdict disagrees)
        def fake_screen_drop(r):
            s = r["order"].pop()
            r["drops"][str(s)] = "screen-outlier"
        assert why(fake_screen_drop) == "screen-replay-mismatch"
        # wrong init: claiming a zeros start while the self frames say
        # the owner contributed changes the f32 operation sequence
        def zeros_init(r):
            r["init"] = "zeros"
        assert why(zeros_init) == "wrong-init"
        # dropping the self frames ENTIRELY replays coherently as "the
        # owner contributed nothing" — but the bytes it actually
        # served then disagree, which is the byte-compare's catch
        def no_self(r):
            r["init"] = "zeros"
            r["frames"].pop(str(ra.group.my_index), None)
        tr = _mutated(nodes, ra, no_self)
        res = replay_transcript(tr, **kw)
        honest = replay_transcript(_mutated(nodes, ra, lambda r: None),
                                   **kw)
        assert res.ok and honest.ok
        assert res.values.tobytes() != honest.values.tobytes()
        # an applied sender whose frames were stripped cannot be
        # re-derived
        def strip_frames(r):
            r["frames"].pop(str(r["order"][0]))
        assert why(strip_frames) == "applied-sender-missing-frames"

    def test_fabricated_self_contribution_is_caught(self, round5):
        """The one input an owner CAN mint is its own — a self-segment
        crafted to 'explain' a wrong part is an outlier the replayed
        screen drops, so the claimed keep fails the screen replay."""
        from dalle_tpu.swarm.allreduce import (_chunk_slices, _make_frame,
                                               _sign_ctx)
        nodes, _res, ras, _led, screen = round5
        ra = self._owner_ra(ras)
        owner_pid = ra.owners[ra.my_part].peer_id
        owner_ident = next(nd.identity for nd in nodes
                           if nd.peer_id == owner_pid)
        n = ra.part_sizes[ra.my_part]
        chunks = _chunk_slices(n, ra.chunk_elems)
        ctx = _sign_ctx(ra.prefix, ra.epoch, "scatter", owner_pid)
        fake = (np.ones(n, np.float32) * 1000.0)

        def swap_self(r):
            frames = []
            for ci, (clo, chi) in enumerate(chunks):
                payload = compression.compress(fake[clo:chi],
                                               compression.NONE)
                frames.append(_make_frame(
                    owner_ident, ctx, ra.group.group_hash,
                    ra.group.my_index, 1.0, chi - clo,
                    compression.NONE, payload, chunk=ci,
                    n_chunks=len(chunks)))
            r["frames"][str(ra.group.my_index)] = frames
        tr = _mutated(nodes, ra, swap_self)
        res = replay_transcript(tr, **_replay_kwargs(ra, screen))
        assert not res.ok and res.why == "screen-replay-mismatch"
        assert ra.group.my_index in res.screen_drops

    def test_replay_deterministic_repeated_and_parallel(self, round5):
        """Satellite pin: the drop-set (and bytes) recomputed from a
        transcript are bit-equal across repeated runs AND under
        --jobs-style parallel auditing — the replay is a pure function
        of (transcript, group, config)."""
        nodes, _res, ras, _led, screen = round5
        ra = self._owner_ra(ras)
        tr = _mutated(nodes, ra, lambda raw: None)
        kw = _replay_kwargs(ra, screen)
        ref = replay_transcript(tr, **kw)
        assert ref.ok
        for _ in range(4):
            res = replay_transcript(tr, **kw)
            assert res.screen_drops == ref.screen_drops
            assert res.values.tobytes() == ref.values.tobytes()
        with concurrent.futures.ThreadPoolExecutor(max_workers=4) as ex:
            futs = [ex.submit(replay_transcript, tr, **kw)
                    for _ in range(8)]
            for f in futs:
                res = f.result()
                assert res.screen_drops == ref.screen_drops
                assert res.values.tobytes() == ref.values.tobytes()


# -- byte transparency -----------------------------------------------------

class TestTransparency:
    def test_audit_on_rounds_byte_identical_to_audit_off(self):
        """The tentpole's transparency contract, both directions:
        audit=None rounds are the pre-change protocol, and audit-ON
        honest rounds produce byte-identical averages (retention
        copies bytes, never touches the accumulation)."""
        tensors = _int_tensors(5, seed=13)
        nodes = _det_swarm(5, base=81)
        try:
            on, _ras, led_on = _audited_round(nodes, "ta", tensors,
                                              audit_on=True)
        finally:
            for nd in nodes:
                nd.shutdown()
        nodes = _det_swarm(5, base=81)
        try:
            off, _r2, led_off = _audited_round(nodes, "tb", tensors,
                                               audit_on=False)
        finally:
            for nd in nodes:
                nd.shutdown()
        for i in range(5):
            a = flatten_tensors(on[i][1])
            b = flatten_tensors(off[i][1])
            assert a.tobytes() == b.tobytes()
        assert all(not led.snapshot() for led in led_on + led_off)

    def test_multichunk_round_replays_clean(self):
        """Parts split into many wire chunks (chunk_elems << part
        size): retention, transcript reassembly and replay all work
        per chunk, and a hostile SENDER shipping inconsistent
        in-clamp weights across its chunks cannot frame the honest
        owner — the chunk-0 claim governs on both the live path and
        the replay (the review-found framing attack)."""
        from dalle_tpu.swarm.allreduce import (_chunk_slices, _make_frame,
                                               _parse, _sign_ctx)
        nodes = _det_swarm(5, base=101)
        try:
            screen = GradientScreen(ScreenPolicy())
            results, ras, ledgers = _audited_round(
                nodes, "mc", _int_tensors(5, seed=21), screen=screen,
                chunk_elems=32)
            reports = [audit_round(nodes[i], ras[i], ledgers[i])
                       for i in range(5)]
            for rep, led in zip(reports, ledgers):
                assert not rep["failed"] and not rep["unserved"] \
                    and not rep["omitted"], rep
                assert led.snapshot() == {}
            # now the framing attempt: rewrite one applied sender's
            # NON-ZERO chunk to claim a different (in-clamp) weight
            # and re-sign with that sender's REAL key — the replay
            # must still pass with unchanged values
            ra = next(r for r in ras if r.audits_mine)
            owner_pid = ra.owners[ra.my_part].peer_id
            sender = next(s for s in ra.order)
            sender_pid = ra.group.members[sender].peer_id
            sender_ident = next(nd.identity for nd in nodes
                                if nd.peer_id == sender_pid)
            chunks = _chunk_slices(ra.part_sizes[ra.my_part],
                                   ra.chunk_elems)
            assert len(chunks) > 1
            ctx = _sign_ctx("mc", 0, "scatter", owner_pid)
            honest = _mutated(nodes, ra, lambda r: None)
            kw = _replay_kwargs(ra, screen)
            ref = replay_transcript(honest, **kw)
            assert ref.ok, ref.why

            def twist_weight(r):
                frames = list(r["frames"][str(sender)])
                for i, raw in enumerate(frames):
                    p = _parse(raw, ra.group, chunks, ctx)
                    if p is not None and p[0] == "ok" and p[3] == 1:
                        clo, chi = chunks[1]
                        payload = compression.compress(p[4],
                                                       compression.NONE)
                        frames[i] = _make_frame(
                            sender_ident, ctx, ra.group.group_hash,
                            sender, 9.0, chi - clo, compression.NONE,
                            payload, chunk=1, n_chunks=len(chunks))
                r["frames"][str(sender)] = frames
            twisted = _mutated(nodes, ra, twist_weight)
            res = replay_transcript(twisted, **kw)
            assert res.ok, res.why
            assert res.values.tobytes() == ref.values.tobytes()
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_below_quorum_owner_cannot_mint_over_ceiling_self(self):
        """The docstring's below-quorum defense, both faces: a 2-peer
        round where one OWNER's own data is over the ceiling withholds
        that contribution live (unstruck — the small-swarm rule), and
        a forged transcript claiming such a self-contribution was KEPT
        fails the replay."""
        nodes = _det_swarm(2, base=111)
        big_i = 1
        base = (np.arange(300, dtype=np.float32) % 7 - 3)
        tensors = [[base.copy()], [np.full(300, 1000.0, np.float32)]]
        screen = GradientScreen(ScreenPolicy(abs_norm_ceiling=500.0))
        try:
            results, ras, ledgers = _audited_round(
                nodes, "sq", tensors, screen=screen)
            reports = [audit_round(nodes[i], ras[i], ledgers[i])
                       for i in range(2)]
            # live: every part ends as the honest peer's values alone
            # (big_i's data is withheld everywhere), honest replays
            # pass, nobody is struck
            for i in range(2):
                assert not reports[i]["failed"] \
                    and not reports[i]["unserved"], reports[i]
                assert ledgers[i].snapshot() == {}
                got = flatten_tensors(results[i][1])
                np.testing.assert_array_equal(got,
                                              flatten_tensors(tensors[0]))
            # forged face: rewrite the big owner's transcript to CLAIM
            # it kept its over-ceiling self-contribution
            ra = ras[big_i]
            assert ra.audits_mine and ra.init == "zeros"
            assert ra.drops.get(ra.group.my_index) == "screen-outlier"

            def keep_self(r):
                r["init"] = "self"
                r["drops"].pop(str(ra.group.my_index))
            tr = _mutated(nodes, ra, keep_self)
            res = replay_transcript(tr, **_replay_kwargs(ra, screen))
            assert not res.ok
            assert res.why == "kept-over-ceiling-sender"
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_u8_codec_round_replays_bit_exact(self):
        """The replay reproduces the lossy wire round-trip exactly —
        the u8-quantized gathered bytes ARE the comparison target."""
        nodes = _det_swarm(5, base=41)
        try:
            screen = GradientScreen(ScreenPolicy())
            results, ras, ledgers = _audited_round(
                nodes, "u8", _int_tensors(5, seed=3), screen=screen,
                codec=compression.UNIFORM8BIT)
            reports = [audit_round(nodes[i], ras[i], ledgers[i])
                       for i in range(5)]
        finally:
            for nd in nodes:
                nd.shutdown()
        for rep, led in zip(reports, ledgers):
            assert not rep["failed"] and not rep["unserved"] \
                and not rep["omitted"]
            assert led.snapshot() == {}
            assert len(rep["ok"]) == 4


# -- quantized wire + error feedback (r15) ---------------------------------

class TestQuantizedAudit:
    def test_ef_quantized_rounds_replay_bit_exact_across_epochs(self):
        """The r15 trust-layer carry-over: two consecutive rounds on
        the pinned u8-reduce/u4-gather wire with PERSISTENT per-peer
        error-feedback residuals and a PARTIAL challenge (frac=0.5,
        prefix chosen so the challenged set flips between epochs).
        Unchallenged parts carry their owner's gather residual;
        challenged parts suspend the carry — so every audited part
        must replay bit-exactly even while live residuals exist, and
        honest owners earn zero strikes. Real-valued (codec-inexact)
        gradients: the quantization error is genuinely nonzero."""
        from dalle_tpu.swarm.error_feedback import make_pair
        rng = np.random.RandomState(3)
        nodes = _det_swarm(5, base=121)
        efs = [make_pair() for _ in range(5)]
        policy = AuditPolicy(frac=0.5, fetch_timeout=2.0)
        try:
            gather_resid_seen = False
            for epoch in (0, 1):
                tensors = [[(rng.randn(640) * (1 + i)).astype(np.float32)]
                           for i in range(5)]
                results, ras, ledgers = _audited_round(
                    nodes, "qa0", tensors, policy=policy,
                    codec=compression.UNIFORM8BIT,
                    gather_codec=compression.UNIFORM4BIT,
                    efs=efs, chunk_elems=1024, epoch=epoch)
                assert challenged_parts("qa0", epoch, 5, 0.5), \
                    "prefix must challenge at least one part"
                # every member's replay of every challenged part passes
                for i in range(5):
                    rep = audit_round(nodes[i], ras[i], ledgers[i])
                    assert rep["audited"], rep
                    assert not rep["failed"] and not rep["unserved"] \
                        and not rep["omitted"], (epoch, i, rep)
                    assert ledgers[i].snapshot() == {}
                # all members ended byte-identical (the wire contract)
                flats = [flatten_tensors(r[1]) for r in results]
                for f in flats[1:]:
                    assert flats[0].tobytes() == f.tobytes()
                # the feedback loop is LIVE: scatter residuals are
                # nonzero (real quantization error), and at least one
                # owner carries a nonzero gather residual
                for sc, _ga in efs:
                    r = sc.residual_host()
                    assert r is not None and np.abs(r).max() > 0
                gather_resid_seen = gather_resid_seen or any(
                    ga.residual_host() is not None
                    and np.abs(ga.residual_host()).max() > 0
                    for _sc, ga in efs)
            assert gather_resid_seen
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_unpinned_mixed_codec_round_replays_clean(self):
        """A round whose callers pass an explicit codec WITHOUT
        opting into pinning accepts mixed-codec senders (r14
        semantics) — and the replay must apply the SAME acceptance
        rule: an honest owner that applied a legitimately
        differently-coded frame is never convicted (the review-found
        live-vs-replay asymmetry)."""
        from dalle_tpu.swarm.allreduce import CHUNK_ELEMS
        nodes = _det_swarm(5, base=161)
        rng = np.random.RandomState(9)
        tensors = [[(rng.randn(400) * (1 + i)).astype(np.float32)]
                   for i in range(5)]
        policy = AuditPolicy(frac=1.0, fetch_timeout=2.0)
        screen = GradientScreen(ScreenPolicy())
        ledgers = [PeerHealthLedger() for _ in range(5)]
        ras = [RoundAudit("mxr", 0, policy) for _ in range(5)]
        try:
            def peer(i):
                g = make_group(nodes[i], "mxr", epoch=0, weight=1.0,
                               matchmaking_time=2.0, min_group_size=5)
                assert g is not None and g.size == 5
                # peer 4 runs SizeAdaptive (f16 at these sizes); the
                # rest pass u8 explicitly but UNPINNED
                return g, run_allreduce(
                    nodes[i], g, "mxr", 0, tensors[i], weight=1.0,
                    allreduce_timeout=8.0, sender_timeout=1.5,
                    codec=None if i == 4 else compression.UNIFORM8BIT,
                    ledger=ledgers[i], screen=screen,
                    max_peer_weight=100.0, audit=ras[i],
                    chunk_elems=CHUNK_ELEMS)

            _run_threads([lambda i=i: peer(i) for i in range(5)])
            for i in range(5):
                rep = audit_round(nodes[i], ras[i], ledgers[i])
                assert rep["audited"], rep
                assert not rep["failed"] and not rep["unserved"] \
                    and not rep["omitted"], (i, rep)
                assert ledgers[i].snapshot() == {}
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_replay_uses_the_gather_codec(self):
        """A round whose two legs pin DIFFERENT codecs: the replay
        must re-quantize with the GATHER codec — replaying the same
        transcript under the wrong gather codec mismatches the
        gathered bytes (the codec is load-bearing, not decorative)."""
        nodes = _det_swarm(4, base=141)
        rng = np.random.RandomState(7)
        tensors = [[(rng.randn(512) * (1 + i)).astype(np.float32)]
                   for i in range(4)]
        try:
            results, ras, ledgers = _audited_round(
                nodes, "qg", tensors, codec=compression.UNIFORM8BIT,
                gather_codec=compression.UNIFORM4BIT, chunk_elems=1024)
            auditor = next(
                r for r in ras
                if any(p != r.my_part and p in r.gathered
                       for p in r.audited))
            part = next(p for p in sorted(auditor.audited)
                        if p != auditor.my_part and p in auditor.gathered)
            owner = auditor.owners[part]
            blob = fetch_transcript(
                nodes[ras.index(auditor)], owner.addr, "qg", 0, part,
                auditor.policy, group_key=auditor.group.group_key)
            tr = open_transcript(blob, "qg", 0, part, owner.peer_id)
            assert tr is not None
            right = replay_transcript(
                tr, group=auditor.group, prefix="qg", epoch=0,
                part=part, part_elems=auditor.part_sizes[part],
                chunk_elems=1024, codec=compression.UNIFORM8BIT,
                adaptive_threshold=auditor.adaptive_threshold,
                screen=auditor.screen, max_peer_weight=100.0,
                gather_codec=compression.UNIFORM4BIT)
            assert right.ok
            assert right.values.tobytes() == \
                auditor.gathered[part].tobytes()
            wrong = replay_transcript(
                tr, group=auditor.group, prefix="qg", epoch=0,
                part=part, part_elems=auditor.part_sizes[part],
                chunk_elems=1024, codec=compression.UNIFORM8BIT,
                adaptive_threshold=auditor.adaptive_threshold,
                screen=auditor.screen, max_peer_weight=100.0,
                gather_codec=compression.UNIFORM8BIT)
            assert wrong.ok  # internally consistent transcript...
            assert wrong.values.tobytes() != \
                auditor.gathered[part].tobytes()  # ...wrong bytes
        finally:
            for nd in nodes:
                nd.shutdown()


# -- live conviction -------------------------------------------------------

class TestConviction:
    def test_wrong_part_owner_convicted_by_every_honest_member(self):
        nodes = _det_swarm(5, base=51)
        pids = [nd.peer_id for nd in nodes]
        bad_i = 2
        dhts = list(nodes)
        dhts[bad_i] = ChaosDHT(nodes[bad_i], FaultPlan(
            seed=1, byzantine=(ByzantineOp(kind="wrong_gather_part",
                                           factor=10.0),)))
        try:
            screen = GradientScreen(ScreenPolicy())
            results, ras, ledgers = _audited_round(
                nodes, "wg", _int_tensors(5), dhts=dhts, screen=screen)
            reports = [audit_round(dhts[i], ras[i], ledgers[i],
                                   jobs=2)
                       for i in range(5)]
        finally:
            for nd in nodes:
                nd.shutdown()
        bad_part = next(k for k, m in enumerate(ras[0].owners)
                        if m.peer_id == pids[bad_i])
        for i in range(5):
            if i == bad_i:
                continue
            assert [f["part"] for f in reports[i]["failed"]] == [bad_part]
            assert reports[i]["failed"][0]["why"] \
                == "replayed-bytes-mismatch"
            assert ledgers[i].score(pids[bad_i]) == pytest.approx(2.0)
            # honest owners still audit clean against each other
            assert len(reports[i]["ok"]) == 3

    def test_omitting_owner_convicted_by_its_victim(self):
        nodes = _det_swarm(5, base=31)
        pids = [nd.peer_id for nd in nodes]
        bad_i = 1
        dhts = list(nodes)
        dhts[bad_i] = ChaosDHT(nodes[bad_i], FaultPlan(
            seed=2, byzantine=(ByzantineOp(kind="omit_sender"),)))
        try:
            screen = GradientScreen(ScreenPolicy())
            results, ras, ledgers = _audited_round(
                nodes, "om", _int_tensors(5, seed=7), dhts=dhts,
                screen=screen)
            reports = [audit_round(dhts[i], ras[i], ledgers[i])
                       for i in range(5)]
        finally:
            for nd in nodes:
                nd.shutdown()
        victim = pids.index(min(p for i, p in enumerate(pids)
                                if i != bad_i))
        for i in range(5):
            if i == bad_i:
                continue
            if i == victim:
                assert [o["owner"] for o in reports[i]["omitted"]] \
                    == [pids[bad_i]]
                assert ledgers[i].score(pids[bad_i]) == pytest.approx(2.0)
            else:
                # non-victims have no standing: the omitted set was
                # honestly averaged, their replay passes
                assert not reports[i]["omitted"]
                assert ledgers[i].score(pids[bad_i]) == 0.0

    def test_unserved_transcript_is_an_audit_timeout_strike(self):
        class _DropAuditPosts:
            """An owner that stonewalls the audit: every transcript
            post is silently swallowed."""

            def __init__(self, inner, suppressed):
                self._inner = inner
                self._suppressed = suppressed

            def post(self, tag, payload, expiration_time):
                if tag in self._suppressed:
                    return True
                return self._inner.post(tag, payload, expiration_time)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        nodes = _det_swarm(5, base=21)
        pids = [nd.peer_id for nd in nodes]
        bad_i = 3
        suppressed = {_audit_tag("ns", 0, part, ci)
                      for part in range(5) for ci in range(8)}
        dhts = list(nodes)
        dhts[bad_i] = _DropAuditPosts(nodes[bad_i], suppressed)
        policy = AuditPolicy(frac=1.0, fetch_timeout=0.5,
                             fetch_retries=1)
        try:
            screen = GradientScreen(ScreenPolicy())
            results, ras, ledgers = _audited_round(
                nodes, "ns", _int_tensors(5, seed=11), dhts=dhts,
                screen=screen, policy=policy)
            reports = [audit_round(dhts[i], ras[i], ledgers[i])
                       for i in range(5)]
        finally:
            for nd in nodes:
                nd.shutdown()
        for i in range(5):
            if i == bad_i:
                continue
            assert [u["owner"] for u in reports[i]["unserved"]] \
                == [pids[bad_i]]
            # timeout-weighted and LOCAL: stonewalling converges to a
            # down-ranking without any gossip amplification
            assert ledgers[i].score(pids[bad_i]) == pytest.approx(1.0)


# -- the worker ------------------------------------------------------------

class TestAuditWorker:
    def test_step_drains_and_counts(self):
        nodes = _det_swarm(5, base=11)
        try:
            screen = GradientScreen(ScreenPolicy())
            results, ras, ledgers = _audited_round(
                nodes, "wk", _int_tensors(5, seed=2), screen=screen)
            w = AuditWorker(nodes[0], ledgers[0], jobs=2)
            w.submit(ras[0])
            w.submit(None)                      # ignored
            w.submit(RoundAudit("wk", 9))       # never begun: ignored
            assert w.step() == 1
            assert w.audited == 4 and w.failures == 0
            assert w.unserved == 0 and w.omissions == 0
            assert ledgers[0].snapshot() == {}
        finally:
            for nd in nodes:
                nd.shutdown()

    def test_queue_bound_drops_oldest(self):
        w = AuditWorker(None, None)
        ras = []
        for e in range(AuditWorker.MAX_PENDING + 2):
            ra = RoundAudit("qb", e)
            ra.begun = True
            ras.append(ra)
            w.submit(ra)
        with w._lock:
            epochs = [r.epoch for r in w._pending]
        assert len(epochs) == AuditWorker.MAX_PENDING
        assert epochs[0] == 2  # the two oldest were dropped

    def test_worker_thread_stops_clean(self):
        w = AuditWorker(None, None, period=0.05)
        w.start()
        time.sleep(0.15)
        w.stop()
        assert not w.is_alive()


# -- the hostile-owner soak gate -------------------------------------------

class TestHostileOwnerSoak:
    def test_schedule_is_seed_deterministic(self):
        from scripts.churn_soak import build_hostile_schedule
        a = build_hostile_schedule(seed=4, n_peers=5, epochs=3)
        b = build_hostile_schedule(seed=4, n_peers=5, epochs=3)
        c = build_hostile_schedule(seed=5, n_peers=5, epochs=3)
        assert a == b and a != c
        grads = [x for x in a["attacks"] if x["phase"] == "grads"]
        assert sorted(x["kind"] for x in grads) \
            == ["omit_sender", "wrong_gather_part"]
        assert len({x["peer"] for x in grads}) == 2
        # r16: the same two hostile peers each also attack one aux
        # averaging phase, paired with distinct honest partners
        aux = a["aux"]
        assert set(aux) == {"p", "state"}
        attackers = {x["peer"] for x in grads}
        for pair in aux.values():
            assert pair["attacker"] in attackers
            assert pair["partner"] not in attackers
        assert aux["p"]["partner"] != aux["state"]["partner"]
        phases = sorted(x["phase"] for x in a["attacks"])
        assert phases == ["grads", "grads", "powersgd", "state"]

    def test_fast_soak(self, tmp_path):
        """Tier-1 hostile-owner + REPAIR gate (the r16 repair soak):
        5 peers, FOUR passes over one schedule — control (audits +
        repair + aux phases on: zero strikes, ZERO repairs, bit-exact),
        attack (wrong-part conviction triggers repair and repaired
        survivors match the honest-only analytic reference; the
        PowerSGD-factor and state-averaging owner attacks each convict
        in every honest ledger via a verified proof-carrying receipt,
        at peers holding zero local evidence), nofix (repair OFF == the
        r15 protocol: convicted survivors DIVERGE — the regression
        repair exists to fix), and transparency (audits off == the
        pre-audit protocol)."""
        from scripts.churn_soak import main
        out = tmp_path / "HOSTILE_OWNER_SOAK.json"
        rc = main(["--hostile-owner", "--peers", "5", "--epochs", "3",
                   "--seed", "7", "--matchmaking-time", "1.2",
                   "--allreduce-timeout", "5", "--deadline", "150",
                   "--out", str(out)])
        assert rc == 0, f"hostile-owner soak reported a violation ({out})"
        report = json.loads(out.read_text())
        assert report["pass"] is True and report["violations"] == []
        assert all(not r["first_strike"] for r in report["control"])
        assert all(not r["repairs"].get("applied", 0)
                   for r in report["control"])
        assert all(not any(r["audit_events"].values())
                   for r in report["transparency"])
        honest = [r for r in report["attack"] if not r["attacker"]]
        assert len(honest) == 3
        # convicted ⇒ corrected: every honest member repaired
        assert all(r["repairs"]["applied"] >= 1 for r in honest)
        # r20: with the inline cap forced tiny, every honest peer
        # published its conviction evidence BY REFERENCE and convicted
        # on bundles it FETCHED (digest-checked) from other mailboxes
        assert report["params"]["proof_inline_max"] == 512
        assert all(r["proofs_by_reference"] >= 1 for r in honest)
        assert all(r["proof_fetch"]["ok"] >= 1 for r in honest)
        # r20: the aux pair partners repaired their factor/state
        # averages bit-exactly onto the honest reference
        aux = report["schedule"]["aux"]
        by_index = {r["name"]: r for r in report["attack"]}
        for suffix, pair in aux.items():
            partner = by_index[f"peer{pair['partner']}"]
            assert partner["aux_repairs"].get(suffix, 0) >= 1
            assert partner["aux_repair_clean"].get(suffix) is True
        # r20: the poison phase ran and every audience peer rejected
        # both the unfetchable and the forged by-reference receipt
        assert report["poison"].get("issuer")
        assert report["poison"]["ledger_hits"] == []
        assert all(v >= 2
                   for v in report["poison"]["rejected"].values())
        # and the nofix pass reproduces the r15 divergence the repair
        # closes (honest fingerprints differ from the attack pass's)
        nofix_honest = [r for r in report["nofix"] if not r["attacker"]]
        assert {r["fingerprint"] for r in nofix_honest} \
            != {r["fingerprint"] for r in honest}

    @pytest.mark.slow
    def test_full_soak(self, tmp_path):
        """The full-size hostile-owner soak (defaults-sized windows) —
        slow-marked; `scripts/churn_soak.py --hostile-owner` is the
        same gate from the command line."""
        from scripts.churn_soak import main
        out = tmp_path / "HOSTILE_OWNER_SOAK.json"
        rc = main(["--hostile-owner", "--peers", "5", "--epochs", "6",
                   "--seed", "11", "--deadline", "420",
                   "--out", str(out)])
        assert rc == 0
