"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-chip sharding semantics are exercised without TPUs by spoofing the
host platform device count (the strategy SURVEY.md §4 prescribes; the driver
separately dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).
Must run before jax initializes its backends, hence the env mutation at
import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
