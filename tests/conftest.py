"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-chip sharding semantics are exercised without TPUs by spoofing the
host platform device count (the strategy SURVEY.md §4 prescribes; the driver
separately dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).

The XLA flag must be set before jax initializes its backends, hence the env
mutation at import time. The platform pin must happen AFTER the jax import:
this environment's TPU shim force-rewrites the ``jax_platforms`` config (and
the JAX_PLATFORMS env var) during import, so only a post-import
``config.update`` sticks.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)
assert jax.default_backend() == "cpu" and jax.device_count() >= 8
