"""Averaging-assist aux mode (swarm/assist.py + the weight-0 member
protocol in swarm/allreduce.py): the reference declares this mode and
stubs it with NotImplementedError (run_aux_peer.py:99-104); here it is
implemented and these tests pin its semantics."""

import threading
import time

import numpy as np
import pytest

from dalle_tpu.swarm import compression
from dalle_tpu.swarm.allreduce import flatten_tensors, run_allreduce
from dalle_tpu.swarm.assist import (AveragingAssistant, assist_one_round,
                                    grad_flat_elements)
from dalle_tpu.swarm.matchmaking import make_group
from tests.test_collab import make_swarm, run_threads


@pytest.fixture
def swarm3():
    nodes = make_swarm(3)
    yield nodes
    for n in nodes:
        n.shutdown()


SHAPES = [(33,), (8, 9), (5,)]
N_ELEMS = 33 + 72 + 5


def _tensors(seed):
    rng = np.random.RandomState(seed)
    return [rng.randn(*s).astype(np.float32) for s in SHAPES]


class TestWeightZeroProtocol:
    def test_assistant_owns_part_result_excludes_it(self, swarm3):
        """2 trainers + 1 weight-0 assistant: trainers' results equal the
        weighted mean of the TRAINERS only, identical on both — which
        also proves the assistant reduced and gathered its part (a dead
        or wrong part would leave the trainers' copies divergent)."""
        tensors = [_tensors(0), _tensors(1)]
        weights = [1.0, 3.0]

        def trainer(i):
            # assist_one_round joins the "<run_id>_grads" prefix the
            # collaborative optimizer uses — trainers here do the same
            g = make_group(swarm3[i], "as_grads", epoch=0,
                           weight=weights[i],
                           matchmaking_time=3.0, min_group_size=2)
            assert g is not None and g.size == 3
            # every routable member owns a part, assistant included
            assert sum(1 for m in g.members if m.addr) == 3
            return run_allreduce(swarm3[i], g, "as_grads", 0, tensors[i],
                                 weight=weights[i], allreduce_timeout=10.0,
                                 codec=compression.NONE)

        def assistant():
            template = np.zeros(N_ELEMS, np.float32)
            outcome = assist_one_round(
                swarm3[2],
                _cfg(matchmaking_time=3.0, allreduce_timeout=10.0),
                0, template, codec=compression.NONE)
            assert outcome == "assisted", outcome

        results = run_threads([lambda: trainer(0), lambda: trainer(1),
                               assistant])
        num = (flatten_tensors(tensors[0]) * weights[0]
               + flatten_tensors(tensors[1]) * weights[1])
        want = num / sum(weights)
        for res in results[:2]:
            np.testing.assert_allclose(flatten_tensors(res), want,
                                       rtol=1e-5, atol=1e-6)

    def test_zero_sample_trainer_not_waited_on(self, swarm3):
        """A trainer that accumulated 0 samples contributes nothing and
        receivers must not wait on it — the round completes fast."""
        tensors = [_tensors(0), _tensors(1), _tensors(2)]
        weights = [2.0, 1.0, 0.0]

        def peer(i):
            g = make_group(swarm3[i], "zs", epoch=1, weight=weights[i],
                           matchmaking_time=3.0, min_group_size=2)
            assert g is not None and g.size == 3
            t0 = time.monotonic()
            res = run_allreduce(swarm3[i], g, "zs", 1, tensors[i],
                                weight=weights[i], allreduce_timeout=30.0,
                                codec=compression.NONE)
            return res, time.monotonic() - t0

        out = run_threads([lambda i=i: peer(i) for i in range(3)])
        num = sum(flatten_tensors(t) * w
                  for t, w in zip(tensors[:2], weights[:2]))
        want = num / sum(weights[:2])
        for res, dt in out[:2]:
            np.testing.assert_allclose(flatten_tensors(res), want,
                                       rtol=1e-5, atol=1e-6)
            # no sender_timeout (7.5 s at this budget) was burned waiting
            # for the 0-weight member's nonexistent contribution
            assert dt < 6.0, dt

    def test_assistant_with_no_contributions_withholds_part(self, swarm3):
        """An assistant whose contributors all die mid-round must NOT
        gather its zero template (that would silently zero the part on
        every trainer) — it withholds the part and reports the empty
        round so the loop can raise the config-mismatch alarm."""
        from dalle_tpu.swarm.allreduce import run_allreduce as ar

        def dead_trainer():
            # announce like a trainer, never serve the round
            g = make_group(swarm3[0], "wh", epoch=3, weight=1.0,
                           matchmaking_time=3.0, min_group_size=2)
            assert g is not None

        def assistant():
            g = make_group(swarm3[1], "wh", epoch=3, weight=0.0,
                           matchmaking_time=3.0, min_group_size=2)
            assert g is not None and g.size == 2
            report = {}
            template = [np.zeros(N_ELEMS, np.float32)]
            ar(swarm3[1], g, "wh", 3, template, weight=0.0,
               allreduce_timeout=5.0, codec=compression.NONE,
               report=report)
            assert report["reduced_senders"] == 0
            assert report["complete"] is False
            return report

        run_threads([dead_trainer, assistant])

    def test_assistant_death_degrades_like_dead_owner(self, swarm3):
        """An assistant that vanishes after matchmaking costs the
        trainers only its part's gather (local-fallback elasticity): the
        round returns and the surviving trainers' contributions still
        average."""
        tensors = [_tensors(0), _tensors(1)]

        def trainer(i):
            g = make_group(swarm3[i], "ad", epoch=2, weight=1.0,
                           matchmaking_time=3.0, min_group_size=2)
            assert g is not None and g.size == 3
            report = {}
            res = run_allreduce(swarm3[i], g, "ad", 2, tensors[i],
                                weight=1.0, allreduce_timeout=6.0,
                                codec=compression.NONE, report=report)
            return res, report

        def dead_assistant():
            # announce like an assistant, then never serve the round
            g = make_group(swarm3[2], "ad", epoch=2, weight=0.0,
                           matchmaking_time=3.0, min_group_size=2)
            assert g is not None

        out = run_threads([lambda: trainer(0), lambda: trainer(1),
                           dead_assistant])
        want = (flatten_tensors(tensors[0])
                + flatten_tensors(tensors[1])) / 2.0
        for res, report in out[:2]:
            flat = flatten_tensors(res)
            # the dead assistant's part fell back to local values; the
            # parts owned by live trainers are correctly averaged
            assert report["complete"] is False
            matches = np.isclose(flat, want, rtol=1e-5, atol=1e-6)
            assert 0 < matches.sum() < flat.size


def _cfg(**over):
    from dalle_tpu.config import CollabConfig
    return CollabConfig(run_id="as", encrypt_data_plane=False, **over)


class TestLeaderChoice:
    def test_assistant_never_leads_a_mixed_group(self):
        """Leader = lowest-id CONTRIBUTOR: views that differ only in
        which weight-0 assistants they saw elect the same leader, so an
        assistant's announce racing into some-but-not-all candidate
        views cannot splinter the round into two confirmed rosters."""
        from dalle_tpu.swarm.matchmaking import GroupMember, choose_leader

        def m(pid, w):
            return GroupMember(pid, f"127.0.0.1:{ord(pid[0])}", w, b"",
                               b"")

        trainers = [m("bbb", 2.0), m("ccc", 1.0)]
        assistant = m("aaa", 0.0)  # lowest id in the group
        with_a = sorted([assistant] + trainers, key=lambda x: x.peer_id)
        without = sorted(trainers, key=lambda x: x.peer_id)
        assert choose_leader(with_a).peer_id == "bbb"
        assert choose_leader(without).peer_id == "bbb"
        # an all-assistant lobby still has a deterministic leader
        assert choose_leader([assistant]).peer_id == "aaa"


class TestAssistantLoop:
    def test_grad_flat_elements_matches_param_count(self):
        from dalle_tpu.config import tiny_model_config
        from dalle_tpu.models.dalle import DALLE, init_params
        import jax

        cfg = tiny_model_config()
        n = grad_flat_elements(cfg)
        params = init_params(DALLE(cfg), jax.random.PRNGKey(0))
        want = sum(np.prod(np.asarray(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
        assert n == int(want)

    def test_thread_assists_a_real_round(self, swarm3):
        """AveragingAssistant follows the progress tracker and joins the
        trainers' round; the trainers see a 3-member group."""
        from dalle_tpu.config import tiny_model_config
        from dalle_tpu.swarm.progress import ProgressTracker

        model_cfg = tiny_model_config()
        n = grad_flat_elements(model_cfg)
        cfg = _cfg(matchmaking_time=3.0, allreduce_timeout=10.0,
                   target_batch_size=8)

        assistant = AveragingAssistant(swarm3[2], cfg, model_cfg)
        sizes = []

        def trainer(i):
            rng = np.random.RandomState(i)
            tensors = [rng.randn(n).astype(np.float32)]
            tracker = ProgressTracker(swarm3[i], cfg.run_id,
                                      cfg.target_batch_size)
            tracker.report_local_progress(0, 8, force=True)
            # give the assistant's tracker poll a chance to see us
            time.sleep(1.0)
            g = make_group(swarm3[i], f"{cfg.run_id}_grads", 0,
                           weight=8.0,
                           matchmaking_time=cfg.matchmaking_time,
                           min_group_size=2)
            assert g is not None
            sizes.append(g.size)
            return run_allreduce(swarm3[i], g, f"{cfg.run_id}_grads", 0,
                                 tensors, weight=8.0,
                                 allreduce_timeout=cfg.allreduce_timeout,
                                 codec=compression.NONE)

        assistant.start()
        try:
            results = run_threads([lambda: trainer(0),
                                   lambda: trainer(1)])
            assert sizes == [3, 3]
            np.testing.assert_allclose(
                flatten_tensors(results[0]), flatten_tensors(results[1]),
                rtol=1e-6, atol=1e-7)
            # the assistant's own round trails the trainers' (it may sit
            # out the rest of its matchmaking window first)
            deadline = time.monotonic() + 20.0
            while assistant.rounds_assisted < 1:
                assert time.monotonic() < deadline, \
                    "assistant never assisted"
                time.sleep(0.1)
        finally:
            assistant.stop()
            assistant.join(timeout=30.0)
        assert not assistant.is_alive()
