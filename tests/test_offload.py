"""Host-offloaded optimizer state (training/offload.py).

Parity target: the reference's ``OffloadOptimizer``
(``lib/training/offload.py:10-93``) must be numerically invisible — the
offloaded apply produces exactly the same parameters as the on-device
apply, with the optimizer state resident on the host CPU device.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dalle_tpu.config import OptimizerConfig, tiny_model_config
from dalle_tpu.data.synthetic import SyntheticCodes
from dalle_tpu.models.dalle import DALLE, init_params
from dalle_tpu.optim import make_optimizer
from dalle_tpu.parallel.mesh import batch_sharding, make_mesh
from dalle_tpu.parallel.sharding import shard_train_state
from dalle_tpu.training.offload import (host_device,
                                        make_offloaded_apply_step,
                                        offload_train_state)
from dalle_tpu.training.steps import (TrainState, make_apply_step,
                                      make_grad_step)


def _setup(opt_cfg, mesh):
    cfg = tiny_model_config()
    model = DALLE(cfg)
    params = init_params(model, jax.random.PRNGKey(0))
    tx = make_optimizer(opt_cfg)
    state = TrainState.create(params, tx)
    data = SyntheticCodes(cfg, num_samples=16, seed=0)
    batch = jax.device_put(next(data.batches(8, seed=0)),
                           batch_sharding(mesh))
    grads, _ = jax.jit(make_grad_step(model))(params, batch)
    return tx, state, grads


def test_offloaded_apply_matches_on_device():
    mesh = make_mesh(dp=2, fsdp=2, tp=2, sp=1)
    for opt_cfg in (OptimizerConfig(warmup_steps=2, total_steps=10,
                                    state_bits=32),
                    OptimizerConfig(warmup_steps=2, total_steps=10,
                                    state_bits=8, min_8bit_size=16)):
        tx, state, grads = _setup(opt_cfg, mesh)

        on_dev = shard_train_state(mesh, state)
        on_dev = jax.jit(make_apply_step(tx))(on_dev, grads)

        off = offload_train_state(mesh, state)
        off = make_offloaded_apply_step(tx, mesh)(off, grads)

        for a, b in zip(jax.tree.leaves(off.params),
                        jax.tree.leaves(on_dev.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)
        assert int(off.step) == int(on_dev.step) == 1


def test_offloaded_state_lives_on_host_and_params_on_mesh():
    mesh = make_mesh(dp=2, fsdp=2, tp=2, sp=1)
    tx, state, grads = _setup(
        OptimizerConfig(warmup_steps=2, total_steps=10, state_bits=32), mesh)
    off = offload_train_state(mesh, state)
    cpu = host_device()

    def devices_of(x):
        return {getattr(s, "device", s) for s in (
            x.sharding.device_set if hasattr(x.sharding, "device_set")
            else [x.devices()])}

    for leaf in jax.tree.leaves(off.opt_state):
        assert leaf.sharding.device_set == {cpu}, leaf
    # params ride the mesh, not the host
    some_param = jax.tree.leaves(off.params)[0]
    assert cpu not in some_param.sharding.device_set or len(
        some_param.sharding.device_set) > 1

    # state remains host-resident across applies
    off = make_offloaded_apply_step(tx, mesh)(off, grads)
    for leaf in jax.tree.leaves(off.opt_state):
        assert leaf.sharding.device_set == {cpu}

    # and a second apply works on the donated/updated state
    off2 = make_offloaded_apply_step(tx, mesh)(off, grads)
    assert int(off2.step) == 2


def test_task_wires_offload():
    from dalle_tpu.config import (CollabConfig, PeerConfig, TrainerConfig)
    from dalle_tpu.task import TrainingTask

    task = TrainingTask(
        model=tiny_model_config(),
        optimizer=OptimizerConfig(warmup_steps=2, total_steps=10,
                                  offload=True, state_bits=32),
        trainer=TrainerConfig(dp=2, fsdp=2, tp=2, per_device_batch=1),
        collab=CollabConfig(),
        peer=PeerConfig())
    cpu = host_device()
    state = task.train_state
    for leaf in jax.tree.leaves(state.opt_state):
        assert leaf.sharding.device_set == {cpu}
    grads, _ = task.grad_step(state.params, next(task.batches()))
    new_state = task.apply_step(state, grads)
    assert int(new_state.step) == 1
    for leaf in jax.tree.leaves(new_state.opt_state):
        assert leaf.sharding.device_set == {cpu}
