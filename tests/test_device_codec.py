"""Device-side wire codec (swarm/device_codec.py): byte parity with the
host codec in BOTH directions, checked-in wire-format goldens, the Pallas
wire-quant kernel, the bundled crypto fallback's RFC vectors, and the
device-backend butterfly all-reduce end-to-end on CPU (the CI face of the
TPU path — same jitted programs, same pipelined decode drain)."""

import logging
import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.swarm import DHT, Identity, compression, device_codec
from dalle_tpu.swarm.allreduce import flatten_tensors, run_allreduce
from dalle_tpu.swarm.matchmaking import make_group

U8 = compression.UNIFORM8BIT
U4 = compression.UNIFORM4BIT
F16 = compression.FLOAT16


def _payload(rng, n):
    """Mixed-magnitude data exercising subnormal-adjacent scales, exact
    zeros, and round-half-even ties inside one buffer."""
    x = (rng.normal(size=n) * rng.choice([1e-6, 1.0, 100.0], size=n)
         ).astype(np.float32)
    x[: n // 3] = 0.0
    return x


class TestByteParity:
    # sizes hit: single partial block, exact block, block+1 (padding
    # tail), many blocks + tail (non-multiple-of-block-size), ODD sizes
    # (the u4 pad nibble), and the SizeAdaptive threshold neighborhood.
    # 1023/1024/1025 are the u4 block's own boundary.
    SIZES = [1, 5, 255, 256, 257, 1000, 1023, 1024, 1025,
             2 ** 16, 2 ** 16 + 7]

    @pytest.mark.parametrize("n", SIZES)
    @pytest.mark.parametrize("codec", [U8, U4, F16, compression.NONE])
    def test_encode_bytes_identical(self, n, codec):
        x = _payload(np.random.default_rng(n), n)
        assert device_codec.compress(x, codec) == \
            compression.compress(x, codec)

    @pytest.mark.parametrize("n", [255, 256, 257, 1023, 1025, 5000])
    @pytest.mark.parametrize("codec", [U8, U4, F16])
    def test_cross_decode_both_directions(self, n, codec):
        x = _payload(np.random.default_rng(n + 1), n)
        host_buf = compression.compress(x, codec)
        dev_buf = device_codec.compress(x, codec)
        # device-encoded buffers decode with the host decompress...
        np.testing.assert_array_equal(
            compression.decompress(dev_buf, codec, n),
            compression.decompress(host_buf, codec, n))
        # ...and host-encoded buffers decode with the device decompress,
        # to identical floats
        np.testing.assert_array_equal(
            device_codec.decompress(host_buf, codec, n),
            compression.decompress(host_buf, codec, n))

    def test_zero_block_and_all_zero(self):
        z = np.zeros(600, np.float32)
        assert device_codec.compress(z, U8) == compression.compress(z, U8)
        np.testing.assert_array_equal(
            device_codec.decompress(compression.compress(z, U8), U8, 600),
            0.0)
        # one zero block among live blocks (scale-0 safe-divide path)
        x = _payload(np.random.default_rng(9), 1024)
        x[256:512] = 0.0
        assert device_codec.compress(x, U8) == compression.compress(x, U8)

    def test_round_half_even_ties(self):
        # absmax 127 -> scale exactly 1.0: integer+0.5 values are exact
        # codebook midpoints, so any rounding-rule drift flips bytes
        t = np.tile(np.array([0.5, 1.5, 2.5, -0.5, -1.5, 127.0, -127.0,
                              63.5], np.float32), 64)
        assert device_codec.compress(t, U8) == compression.compress(t, U8)
        # the u4 face: absmax 7 -> scale 1.0, same midpoint trap
        t4 = np.tile(np.array([0.5, 1.5, 2.5, -0.5, -1.5, 7.0, -7.0,
                               3.5], np.float32), 256)
        assert device_codec.compress(t4, U4) == \
            compression.compress(t4, U4)

    def test_device_array_input(self):
        x = _payload(np.random.default_rng(3), 4096)
        d = jnp.asarray(x)
        for codec in (U8, F16):
            assert device_codec.compress(d, codec) == \
                compression.compress(x, codec)

    def test_f16_bit_exact_roundtrip(self):
        x = _payload(np.random.default_rng(4), 1000)
        buf = device_codec.compress(x, F16)
        assert buf == np.clip(x, np.finfo(np.float16).min,
                              np.finfo(np.float16).max
                              ).astype(np.float16).tobytes()

    def test_bad_codec_and_short_buffer(self):
        with pytest.raises(ValueError):
            device_codec.compress(np.zeros(4, np.float32), 99)
        with pytest.raises(ValueError):
            device_codec.decompress(b"\x00\x00\x01\x00", U8, 256)


class TestWireGolden:
    """Checked-in tiny buffers: an accidental wire-format change (header
    width, scale placement, block size, endianness) fails HERE first,
    not in a cross-peer run."""

    X = np.array([0.0, 0.5, -1.0, 127.0, -127.0, 63.5], np.float32)
    GOLD_U8 = bytes.fromhex("000000060000803f80807fff01c0")
    GOLD_F16 = bytes.fromhex("0000003800bcf057f0d7f053")
    Y = np.array([3e-5, -2.5e-5, 1e-5, 0.0], np.float32)
    GOLD_U8_SMALL = bytes.fromhex("00000004caa37d34ff16aa80")
    # u4: two codes per byte (LOW nibble first), code 8 = zero, one f32
    # scale per 1024-element block. Z's absmax 7 makes the scale exactly
    # 1.0, so the bytes also pin round-half-even at the nibble level
    # (0.5 -> code 8, 3.5 -> code 12).
    GOLD_U4 = bytes.fromhex("000000069224914188f8c1")
    Z = np.array([0.0, 0.5, -1.0, 7.0, -7.0, 3.5], np.float32)
    GOLD_U4_UNIT = bytes.fromhex("000000060000803f88f7c1")

    @pytest.mark.parametrize("impl", [compression, device_codec])
    def test_u8_golden(self, impl):
        assert impl.compress(self.X, U8) == self.GOLD_U8
        assert impl.compress(self.Y, U8) == self.GOLD_U8_SMALL

    @pytest.mark.parametrize("impl", [compression, device_codec])
    def test_u4_golden(self, impl):
        assert impl.compress(self.X, U4) == self.GOLD_U4
        assert impl.compress(self.Z, U4) == self.GOLD_U4_UNIT

    @pytest.mark.parametrize("impl", [compression, device_codec])
    def test_f16_golden(self, impl):
        assert impl.compress(self.X, F16) == self.GOLD_F16

    @pytest.mark.parametrize("impl", [compression, device_codec])
    def test_golden_decodes(self, impl):
        got = impl.decompress(self.GOLD_U8[:], U8, 6)
        # code 128+k decodes to exactly k * scale with scale 1.0 here
        np.testing.assert_array_equal(
            got, np.array([0, 0, -1, 127, -127, 64], np.float32))
        got4 = impl.decompress(self.GOLD_U4_UNIT[:], U4, 6)
        np.testing.assert_array_equal(
            got4, np.array([0, 0, -1, 7, -7, 4], np.float32))


class TestEncodedPart:
    """Whole-part device encode: chunk payload slicing and the local-
    apply decode must match per-chunk host compression byte for byte."""

    def test_chunk_payloads_match_host(self):
        rng = np.random.default_rng(0)
        flat = _payload(rng, 3000)
        enc = device_codec.encode_part(jnp.asarray(flat), 100, 2900)
        part = flat[100:2900]
        chunks = [(0, 512), (512, 1024), (1024, 2560), (2560, 2800)]
        for clo, chi in chunks:
            assert device_codec.part_payload(enc, clo, chi) == \
                compression.compress(part[clo:chi], U8)
            np.testing.assert_array_equal(
                device_codec.part_decode(enc, clo, chi),
                compression.decompress(
                    compression.compress(part[clo:chi], U8), U8,
                    chi - clo))

    def test_unaligned_chunk_start_rejected(self):
        enc = device_codec.encode_part(jnp.zeros(1024, jnp.float32),
                                       0, 1024)
        with pytest.raises(AssertionError):
            device_codec.part_payload(enc, 100, 612)

    def test_host_source(self):
        flat = _payload(np.random.default_rng(1), 700)
        enc = device_codec.encode_part(flat, 0, 700)
        assert device_codec.part_payload(enc, 0, 700) == \
            compression.compress(flat, U8)

    def test_u4_chunk_payloads_match_host(self):
        """The u4 whole-part encode: chunk boundaries are 1024-block
        (hence nibble-pair) aligned, so byte slicing reproduces the
        per-chunk host compression — odd-length final chunk included
        (the pad nibble)."""
        rng = np.random.default_rng(5)
        flat = _payload(rng, 6000)
        enc = device_codec.encode_part(jnp.asarray(flat), 512, 5535, U4)
        part = flat[512:5535]
        chunks = [(0, 1024), (1024, 4096), (4096, 5023)]
        for clo, chi in chunks:
            assert device_codec.part_payload(enc, clo, chi) == \
                compression.compress(part[clo:chi], U4)
            np.testing.assert_array_equal(
                device_codec.part_decode(enc, clo, chi),
                compression.decompress(
                    compression.compress(part[clo:chi], U4), U4,
                    chi - clo))

    def test_unsupported_codec_rejected(self):
        with pytest.raises(ValueError):
            device_codec.encode_part(np.zeros(16, np.float32), 0, 16,
                                     compression.FLOAT16)


class TestFusedAccumulate:
    """The r15 owner hot path: decode + weighted add on device, DONATED
    accumulator, bit-equal to the host multiply-then-add sequence
    (the audit replay's reference semantics)."""

    @pytest.mark.parametrize("codec", [U8, U4])
    def test_bit_parity_with_host_sequence(self, codec):
        rng = np.random.default_rng(11)
        n = 3000
        own = _payload(rng, n)
        acc_h = own * np.float32(1.5)
        acc_d = device_codec.accumulator_init(jnp.asarray(own), 0, n, 1.5)
        assert np.asarray(acc_d).tobytes() == acc_h.tobytes()
        for w in (1.0, 2.5, 0.25):
            seg = _payload(rng, n)
            payload = compression.compress(seg, codec)
            dec = compression.decompress(payload, codec, n)
            acc_h += dec * w
            acc_d = device_codec.fused_accumulate(acc_d, [payload],
                                                  codec, n, w)
            assert np.asarray(acc_d).tobytes() == acc_h.tobytes()

    @pytest.mark.parametrize("codec", [U8, U4])
    def test_multi_chunk_payloads(self, codec):
        """Chunked payloads concatenate into the whole part's codes and
        scales (block-aligned chunk starts), matching the per-chunk
        host decode byte-for-byte."""
        rng = np.random.default_rng(12)
        n = 4096 + 513
        seg = _payload(rng, n)
        chunks = [(0, 1024), (1024, 4096), (4096, n)]
        payloads = [compression.compress(seg[a:b], codec)
                    for a, b in chunks]
        dec = np.concatenate([
            compression.decompress(p, codec, b - a)
            for p, (a, b) in zip(payloads, chunks)])
        acc_h = np.zeros(n, np.float32) + dec * 3.0
        acc_d = device_codec.fused_accumulate(
            jnp.zeros(n, jnp.float32), payloads, codec, n, 3.0)
        assert np.asarray(acc_d).tobytes() == acc_h.tobytes()


class TestPallasWireKernel:
    def test_matches_xla_exactly(self):
        from dalle_tpu.ops.pallas.quant_kernels import \
            wire_quantize_u8_pallas
        x = jnp.asarray(_payload(np.random.default_rng(2), 10_007))
        codes_p, scales_p = wire_quantize_u8_pallas(x, interpret=True)
        codes_x, scales_x = device_codec._enc_u8_xla(x)
        np.testing.assert_array_equal(np.asarray(codes_p),
                                      np.asarray(codes_x))
        np.testing.assert_array_equal(np.asarray(scales_p),
                                      np.asarray(scales_x))

    def test_u4_kernel_matches_xla_exactly(self):
        """The u4 VPU kernel (quantize half; packing is a shared XLA
        byte shuffle) against the XLA path: identical codes and
        scales, so the TPU wire bytes match the host codec's."""
        from dalle_tpu.ops.pallas.quant_kernels import \
            wire_quantize_u4_pallas
        x = jnp.asarray(_payload(np.random.default_rng(6), 10_007))
        codes_p, scales_p = wire_quantize_u4_pallas(x, interpret=True)
        packed_p = device_codec._pack_nibbles(codes_p)
        packed_x, scales_x = device_codec._enc_u4_xla(x)
        np.testing.assert_array_equal(np.asarray(packed_p),
                                      np.asarray(packed_x))
        np.testing.assert_array_equal(np.asarray(scales_p),
                                      np.asarray(scales_x))


class TestFallbackCrypto:
    """The bundled pure-Python/numpy crypto fallback must match its RFCs
    (8032/7748/8439) regardless of whether this host uses it."""

    def test_rfc_vectors(self):
        from dalle_tpu.swarm import _fallback_crypto
        ok, what = _fallback_crypto.self_test()
        assert ok, what

    def test_pem_roundtrip_and_agreement(self):
        from dalle_tpu.swarm import _fallback_crypto as fc
        k = fc.Ed25519PrivateKey.from_private_bytes(b"\x07" * 32)
        pem = k.private_bytes(fc.serialization.Encoding.PEM,
                              fc.serialization.PrivateFormat.PKCS8,
                              fc.serialization.NoEncryption())
        k2 = fc.serialization.load_pem_private_key(pem, password=None)
        msg = b"m" * 32
        assert k2.sign(msg) == k.sign(msg)
        a, b = fc.X25519PrivateKey.generate(), fc.X25519PrivateKey.generate()
        assert a.exchange(b.public_key()) == b.exchange(a.public_key())


def _loopback_swarm(n):
    """Loopback DHT peers with DETERMINISTIC identities: the butterfly
    assigns parts by peer-id sort order, and a part owner's own
    contribution enters its part's average uncompressed (everyone else's
    arrives codec-rounded) — so two rounds are value-comparable only
    when the owner assignment matches."""
    from dalle_tpu.swarm.identity import Ed25519PrivateKey
    nodes = []
    for i in range(n):
        peers = [nodes[0].visible_address] if nodes else []
        ident = Identity(Ed25519PrivateKey.from_private_bytes(
            bytes([61 + i]) * 32))
        nodes.append(DHT(initial_peers=peers, identity=ident,
                         rpc_timeout=5.0))
    return nodes


def _run_round(nodes, groups, arrays_per_peer, backend, chunk_elems,
               codec=None, prefix="dev"):
    import threading
    results, reports = [None] * len(nodes), [dict() for _ in nodes]
    errs = []

    def peer(i):
        try:
            results[i] = run_allreduce(
                nodes[i], groups[i], prefix, 0, arrays_per_peer[i],
                weight=1.0 + i, allreduce_timeout=30.0, codec=codec,
                report=reports[i], chunk_elems=chunk_elems,
                codec_backend=backend)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=peer, args=(i,))
          for i in range(len(nodes))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    return results, reports


class TestAllreduceDeviceBackend:
    """The jitted codec exercised end-to-end through allreduce.py on CPU:
    device-encoded parts ride the same chunked wire, receive-side decodes
    dispatch from the same decode pools, and the bytes (hence the
    averaged values) are identical to the host backend's."""

    def _tensors(self, seed, device=False):
        rng = np.random.default_rng(seed)
        arrs = [_payload(rng, 3000).reshape(50, 60),
                _payload(rng, 700),
                np.zeros(300, np.float32)]
        if device:
            return [jnp.asarray(a) for a in arrs]
        return arrs

    @pytest.mark.parametrize("chunk_elems,codec", [
        (512, U8),     # aligned chunks, forced u8: the whole-part
                       # EncodedPart path (part_payload + part_decode)
        (512, None),   # aligned, SizeAdaptive (f16 at these sizes)
        (300, U8),     # UNALIGNED chunks: the per-chunk device fallback
        (1024, U4),    # aligned u4: whole-part encode + FUSED device
                       # accumulate at the owner (screen=None here)
        (300, U4),     # unaligned u4: per-chunk fallback, fused off
    ])
    def test_matches_host_backend(self, chunk_elems, codec):
        # both backends must produce the same wire bytes, so a 2-peer
        # round gives IDENTICAL averages under either backend
        results = {}
        for backend in ("host", "device"):
            nodes = _loopback_swarm(2)
            try:
                import threading
                gs = [None, None]

                def mk(i):
                    gs[i] = make_group(nodes[i], "g", 0, weight=1.0 + i,
                                       matchmaking_time=2.0,
                                       min_group_size=2, encrypt=True)
                ts = [threading.Thread(target=mk, args=(i,))
                      for i in range(2)]
                [t.start() for t in ts]
                [t.join() for t in ts]
                assert all(g is not None and g.size == 2 for g in gs)
                res, reps = _run_round(
                    nodes, gs,
                    [self._tensors(7, device=(backend == "device")),
                     self._tensors(8)],
                    backend, chunk_elems, codec=codec,
                    prefix=f"p_{backend}_{chunk_elems}_{codec}")
                assert all(r.get("complete") for r in reps)
                results[backend] = res
            finally:
                for nd in nodes:
                    nd.shutdown()
        for r_host, r_dev in zip(results["host"], results["device"]):
            for a, b in zip(r_host, r_dev):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))

    def test_fused_round_interoperates_with_mixed_codec_sender(self):
        """An UNPINNED device u8 round (the fused owner path) must
        still accept a sender whose config picks a different codec —
        r14 mixed-codec interop: the fused path falls back to host
        decode for that sender instead of banning it, and the result
        matches the host backend byte-for-byte."""
        results = {}
        for backend in ("host", "device"):
            nodes = _loopback_swarm(2)
            try:
                import threading
                gs = [None, None]

                def mk(i):
                    gs[i] = make_group(nodes[i], "mx", 0, weight=1.0 + i,
                                       matchmaking_time=2.0,
                                       min_group_size=2, encrypt=True)
                ts = [threading.Thread(target=mk, args=(i,))
                      for i in range(2)]
                [t.start() for t in ts]
                [t.join() for t in ts]
                assert all(g is not None and g.size == 2 for g in gs)
                # peer 0: pinned-arg u8 (fused under the device
                # backend); peer 1: SizeAdaptive (f16 at these sizes)
                res, reps = [None, None], [dict(), dict()]
                errs = []

                def peer(i):
                    try:
                        res[i] = run_allreduce(
                            nodes[i], gs[i], f"mx_{backend}", 0,
                            self._tensors(30 + i,
                                          device=(backend == "device"
                                                  and i == 0)),
                            weight=1.0 + i, allreduce_timeout=20.0,
                            codec=U8 if i == 0 else None,
                            report=reps[i], chunk_elems=512,
                            codec_backend=backend if i == 0 else "host")
                    except Exception as e:  # noqa: BLE001
                        errs.append(repr(e))
                ts = [threading.Thread(target=peer, args=(i,))
                      for i in range(2)]
                [t.start() for t in ts]
                [t.join() for t in ts]
                assert not errs, errs
                assert all(r.get("complete") for r in reps), reps
                assert not reps[0]["corrupt_senders"], reps[0]
                results[backend] = res
            finally:
                for nd in nodes:
                    nd.shutdown()
        for a, b in zip(results["host"], results["device"]):
            for x, y in zip(a, b):
                np.testing.assert_array_equal(np.asarray(x),
                                              np.asarray(y))

    def test_device_arrays_in_device_out_values(self):
        # device-array handoff end to end; trainers end bit-identical
        # and close to the true weighted mean
        nodes = _loopback_swarm(3)
        try:
            import threading
            gs = [None] * 3

            def mk(i):
                gs[i] = make_group(nodes[i], "g3", 0, weight=1.0 + i,
                                   matchmaking_time=2.0,
                                   min_group_size=3, encrypt=False)
            ts = [threading.Thread(target=mk, args=(i,)) for i in range(3)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert all(g is not None and g.size == 3 for g in gs)
            tensors = [self._tensors(20 + i, device=True)
                       for i in range(3)]
            res, reps = _run_round(nodes, gs, tensors, "device", 512,
                                   prefix="p3")
            assert all(r.get("complete") for r in reps)
            flats = [flatten_tensors([np.asarray(x) for x in r])
                     for r in res]
            for f in flats[1:]:
                np.testing.assert_array_equal(flats[0], f)
            want = sum((1.0 + i) * flatten_tensors(
                [np.asarray(x) for x in tensors[i]])
                for i in range(3)) / sum(1.0 + i for i in range(3))
            scale = np.abs(want).max() + 1e-9
            assert np.abs(flats[0] - want).max() / scale < 0.02
        finally:
            for nd in nodes:
                nd.shutdown()


@pytest.mark.slow
def test_payload_scale_device_backend():
    """Moderate-payload (32 MB f32/peer) device-backend round: the
    EncodedPart path at multi-chunk scale with AEAD on — the tier-1-
    excluded face of scripts/swarm_payload_bench.py --device-codec."""
    rng = np.random.default_rng(0)
    n = 8 << 20
    arrays = [[(rng.normal(size=n) * 0.01).astype(np.float32)]
              for _ in range(2)]
    nodes = _loopback_swarm(2)
    try:
        import threading
        gs = [None, None]

        def mk(i):
            gs[i] = make_group(nodes[i], "big", 0, weight=1.0,
                               matchmaking_time=2.0, min_group_size=2,
                               encrypt=True)
        ts = [threading.Thread(target=mk, args=(i,)) for i in range(2)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert all(g is not None and g.size == 2 for g in gs)
        res, reps = _run_round(nodes, gs, arrays, "device",
                               1 << 20, prefix="big")
        assert all(r.get("complete") for r in reps)
        np.testing.assert_array_equal(np.asarray(res[0][0]),
                                      np.asarray(res[1][0]))
    finally:
        for nd in nodes:
            nd.shutdown()
