"""Deterministic fault injection (swarm/chaos.py) + peer health
(swarm/health.py): the chaos wrapper's contracts and the graceful-
degradation paths it exists to exercise.

Three layers, mirroring CHAOS.md:

- wrapper mechanics on a stub transport (no sockets): plan parsing,
  bit-transparency, seed determinism, blackouts, crash-at-epoch;
- the health ledger's strike/decay/penalty arithmetic;
- real-socket integration (test_collab.py idiom — several peers, real
  loopback wire): a corrupted sender is banned-and-renormalized inside
  one allreduce round, a leader that dies between announce and confirm
  doesn't wedge the epoch, and a state-transfer client fails over to a
  different advertised server when its stream goes dark.

The churn soak itself lives in scripts/churn_soak.py; its fast
deterministic variant runs here in tier-1 and the full soak is
slow-marked (pytest.ini).
"""

import hashlib
import threading
import time

import numpy as np
import pytest

from dalle_tpu.swarm import DHT, Identity
from dalle_tpu.swarm import compression
from dalle_tpu.swarm.allreduce import (_part_slices, flatten_tensors,
                                       run_allreduce)
from dalle_tpu.swarm.chaos import (Blackout, ChaosDHT, FaultPlan, FaultRule,
                                   maybe_wrap)
from dalle_tpu.swarm.dht import get_dht_time
from dalle_tpu.swarm.health import PeerHealthLedger
from dalle_tpu.swarm.matchmaking import make_group
from dalle_tpu.swarm.state_transfer import (StateServer,
                                            load_state_from_peers)


# -- stub transport (no sockets) ------------------------------------------

class _StubDHT:
    """Minimal transport double recording what reaches the 'wire'."""

    peer_id = "ab" * 32

    def __init__(self):
        self.sent = []      # (addr, tag, payload)
        self.posted = []
        self.stored = []
        self.inbox = {}     # tag -> payload served by recv
        self.mailbox = {}   # (addr, tag) -> payload served by fetch
        self.records = {}   # key -> value served by get
        self.shutdowns = 0

    def send(self, addr, tag, payload, timeout=None):
        self.sent.append((addr, tag, payload))
        return True

    def recv(self, tag, timeout):
        return self.inbox.get(tag)

    def fetch(self, addr, tag, timeout=None):
        return self.mailbox.get((addr, tag))

    def post(self, tag, payload, expiration_time):
        self.posted.append((tag, payload))
        return True

    def store(self, key, subkey, value, expiration_time):
        self.stored.append((key, subkey, value))
        return True

    def get(self, key, latest=True):
        return self.records.get(key)

    def shutdown(self):
        self.shutdowns += 1


def _wrap(plan, clock=None):
    stub = _StubDHT()
    kwargs = {"clock": clock} if clock is not None else {}
    return stub, ChaosDHT(stub, plan, **kwargs)


class TestFaultPlan:
    def test_json_roundtrip_inline_and_file(self, tmp_path):
        plan = FaultPlan(
            seed=7,
            rules=(FaultRule(ops=("send",), drop=0.25,
                             delay_s=(0.1, 0.2), peers=("beef",)),),
            blackouts=(Blackout(start_s=1.0, end_s=2.0),),
            crash_at_epoch=5)
        again = FaultPlan.from_json(plan.to_json())
        assert again == plan
        p = tmp_path / "plan.json"
        p.write_text(plan.to_json())
        assert FaultPlan.load(str(p)) == plan
        assert FaultPlan.load(plan.to_json()) == plan  # inline form

    def test_unknown_op_rejected(self):
        with pytest.raises(ValueError, match="unknown fault op"):
            FaultPlan.from_dict({"rules": [{"ops": ["sendd"]}]})

    def test_unknown_keys_rejected(self):
        """A typoed fault field must raise, not parse as an inert
        all-defaults clause that makes the soak green while injecting
        nothing."""
        with pytest.raises(ValueError, match="unknown rule key"):
            FaultPlan.from_dict(
                {"rules": [{"ops": ["send"], "corupt": 1.0}]})
        with pytest.raises(ValueError, match="unknown blackout key"):
            FaultPlan.from_dict(
                {"blackouts": [{"start_s": 0.0, "end_s": 1.0,
                                "total": True}]})
        with pytest.raises(ValueError, match="unknown plan key"):
            FaultPlan.from_dict({"seeed": 3})

    def test_enabled(self):
        assert not FaultPlan().enabled
        assert FaultPlan(rules=(FaultRule(),)).enabled
        assert FaultPlan(crash_at_epoch=0).enabled

    def test_maybe_wrap_disabled_returns_same_object(self):
        stub = _StubDHT()
        assert maybe_wrap(stub, None) is stub
        assert maybe_wrap(stub, "") is stub
        # a plan with no rules/blackouts/crash is equally a no-op
        assert maybe_wrap(stub, '{"seed": 9}') is stub

    def test_maybe_wrap_enabled_wraps(self):
        stub = _StubDHT()
        wrapped = maybe_wrap(
            stub, '{"seed": 1, "rules": [{"ops": ["send"], "drop": 1.0}]}')
        assert isinstance(wrapped, ChaosDHT)
        assert wrapped.peer_id == stub.peer_id  # delegation works


class TestChaosWrapper:
    def test_transparent_with_inert_rule(self):
        """A matching rule whose probabilities are all zero must forward
        every call byte-identically — the enabled-but-quiet baseline for
        the zero-behavior-change contract."""
        stub, chaos = _wrap(FaultPlan(rules=(FaultRule(),)))
        stub.inbox[3] = b"in"
        stub.mailbox[("a:1", 4)] = b"mail"
        stub.records["k"] = {"s": 1}
        assert chaos.send("a:1", 2, b"payload") is True
        assert stub.sent == [("a:1", 2, b"payload")]
        assert chaos.recv(3, timeout=0.1) == b"in"
        assert chaos.fetch("a:1", 4) == b"mail"
        assert chaos.post(5, b"posted", get_dht_time() + 5)
        assert stub.posted == [(5, b"posted")]
        assert chaos.store("k2", "s", 7, get_dht_time() + 5)
        assert chaos.get("k") == {"s": 1}
        assert chaos.injected.get("drop", 0) == 0
        assert chaos.injected.get("corrupt", 0) == 0

    def test_same_seed_same_schedule(self):
        """The acceptance contract: identical (seed, peer, op, tag, call
        index) sequence -> identical fault decisions."""
        def pattern(seed):
            stub, chaos = _wrap(FaultPlan(
                seed=seed, rules=(FaultRule(ops=("send",), drop=0.4,
                                            corrupt=0.3),)))
            for i in range(60):
                chaos.send("x:1", 7, bytes([i]) * 33)
            return [p for (_a, _t, p) in stub.sent]

        assert pattern(5) == pattern(5)
        assert pattern(5) != pattern(6)

    def test_corrupt_and_truncate_mutate_payload(self):
        stub, chaos = _wrap(FaultPlan(
            seed=2, rules=(FaultRule(ops=("send",), corrupt=1.0),)))
        chaos.send("x:1", 1, b"A" * 64)
        (_a, _t, wire), = stub.sent
        assert wire != b"A" * 64 and len(wire) == 64
        assert chaos.injected["corrupt"] == 1

        stub2, chaos2 = _wrap(FaultPlan(
            seed=2, rules=(FaultRule(ops=("send",), truncate=1.0),)))
        chaos2.send("x:1", 1, b"A" * 64)
        (_a, _t, wire2), = stub2.sent
        assert len(wire2) < 64 and wire2 == b"A" * len(wire2)

    def test_dropped_send_still_acks(self):
        """Silent loss: the transport reports success, the payload never
        reaches the wire — the nastiest real loss mode."""
        stub, chaos = _wrap(FaultPlan(
            seed=0, rules=(FaultRule(ops=("send",), drop=1.0),)))
        assert chaos.send("x:1", 1, b"gone") is True
        assert stub.sent == []
        assert chaos.injected["drop"] == 1

    def test_peer_pattern_scopes_the_rule(self):
        stub, chaos = _wrap(FaultPlan(
            seed=0, rules=(FaultRule(ops=("send",), drop=1.0,
                                     peers=("10.0.0.9",)),)))
        assert chaos.send("10.0.0.9:1", 1, b"dropped")
        assert chaos.send("10.0.0.8:1", 1, b"delivered")
        assert [a for (a, _t, _p) in stub.sent] == ["10.0.0.8:1"]

    def test_blackout_severs_both_planes_then_heals(self):
        """During the window: sends fail, reads come back empty, inbound
        is consumed-and-discarded. After it: traffic flows again."""
        now = {"t": 0.0}
        stub, chaos = _wrap(
            FaultPlan(blackouts=(Blackout(start_s=1.0, end_s=2.0),)),
            clock=lambda: now["t"])
        stub.inbox[3] = b"in"
        stub.records["k"] = {"s": 1}
        assert chaos.send("x:1", 1, b"pre")          # before: fine
        now["t"] = 1.5                               # inside the window
        assert not chaos.send("x:1", 1, b"cut")
        assert chaos.recv(3, timeout=0.01) is None   # consumed, lost
        assert chaos.get("k") is None
        assert not chaos.store("k", "s", 2, get_dht_time() + 5)
        now["t"] = 2.5                               # healed
        assert chaos.send("x:1", 1, b"post")
        assert chaos.recv(3, timeout=0.01) == b"in"
        assert chaos.get("k") == {"s": 1}
        assert [p for (_a, _t, p) in stub.sent] == [b"pre", b"post"]
        assert chaos.injected["sever"] >= 4

    def test_crash_at_epoch_kills_transport(self):
        stub, chaos = _wrap(FaultPlan(crash_at_epoch=3))
        assert not chaos.note_epoch(2)
        assert chaos.alive and chaos.send("x:1", 1, b"live")
        assert chaos.note_epoch(3)          # fires exactly once
        assert not chaos.note_epoch(4)
        assert not chaos.alive
        assert not chaos.send("x:1", 1, b"dead")
        assert chaos.recv(1, timeout=0.01) is None
        assert chaos.fetch("x:1", 1) is None
        assert chaos.get("k") is None
        assert len(stub.sent) == 1          # nothing after the crash

    def test_rule_time_window(self):
        now = {"t": 0.0}
        stub, chaos = _wrap(
            FaultPlan(rules=(FaultRule(ops=("send",), drop=1.0,
                                       start_s=1.0, end_s=2.0),)),
            clock=lambda: now["t"])
        assert chaos.send("x:1", 1, b"early")
        now["t"] = 1.5
        assert chaos.send("x:1", 1, b"windowed")  # ack'd, dropped
        now["t"] = 3.0
        assert chaos.send("x:1", 1, b"late")
        assert [p for (_a, _t, p) in stub.sent] == [b"early", b"late"]


class TestHealthLedger:
    def test_strikes_accumulate_and_penalize(self):
        led = PeerHealthLedger(ttl_epochs=3, penalty_threshold=3.0)
        led.strike("p1", "reduce-timeout")          # 1.0
        assert not led.penalized("p1")
        led.strike("p1", "corrupt-chunk")           # +2.0 -> 3.0
        assert led.penalized("p1")
        assert led.score("p1") == pytest.approx(3.0)
        assert not led.penalized("p2")
        assert led.snapshot() == {"p1": pytest.approx(3.0)}

    def test_strikes_decay_with_epochs(self):
        led = PeerHealthLedger(ttl_epochs=2, penalty_threshold=2.0)
        led.strike("p1", "corrupt-chunk")
        assert led.penalized("p1")
        led.advance_epoch(1)
        assert led.penalized("p1")   # within the ttl window
        led.advance_epoch(2)         # epoch-0 strike ages out at 0+ttl
        assert not led.penalized("p1")
        assert led.snapshot() == {}  # pruned entirely

    def test_epoch_clock_never_rewinds(self):
        led = PeerHealthLedger(ttl_epochs=1)
        led.advance_epoch(5)
        led.strike("p1", "corrupt-chunk")
        led.advance_epoch(3)         # stale report: ignored
        assert led.score("p1") == pytest.approx(2.0)

    def test_max_peers_bounds_memory(self):
        led = PeerHealthLedger(max_peers=2)
        led.strike("a"), led.strike("b"), led.strike("c")
        assert led.score("c") == 0.0          # flood bound
        led.strike("a")                       # known peer still records
        assert led.score("a") == pytest.approx(2.0)


class TestParseBlameIsAuthenticated:
    """Blame in allreduce must be an authenticated verdict: a frame
    failing the signature check (wire corruption / forgery naming an
    honest peer) is dropped with NO blame, while a VALID signature
    over malformed content convicts the real sender. Anything weaker
    lets any byte flip — or any peer who knows the group hash — evict
    an honest member's contribution and feed the ledger false strikes."""

    @staticmethod
    def _pid(ident):
        # the wire peer id: hex sha256 of the signer's public key
        # (identity.open_frame pins the signer by this)
        import hashlib as _h
        return _h.sha256(ident.public_bytes).hexdigest()

    def _group(self):
        from dalle_tpu.swarm.identity import Ed25519PrivateKey
        from dalle_tpu.swarm.matchmaking import (AveragingGroup,
                                                 GroupMember)
        idents = [Identity(Ed25519PrivateKey.from_private_bytes(
            bytes([60 + i]) * 32)) for i in range(2)]
        members = sorted(
            (GroupMember(peer_id=self._pid(i), addr=f"h:{k}", weight=1.0)
             for k, i in enumerate(idents)), key=lambda m: m.peer_id)
        group = AveragingGroup(members=members, my_index=0,
                               group_hash=b"g" * 16)
        return idents, group

    def _frame(self, ident, group, payload, codec, n, ci=0, nc=1):
        from dalle_tpu.swarm.allreduce import _make_frame
        sender = [m.peer_id for m in group.members].index(
            self._pid(ident))
        return sender, _make_frame(ident, b"ctx", group.group_hash,
                                   sender, 1.0, n, codec, payload,
                                   chunk=ci, n_chunks=nc)

    def test_corrupted_or_forged_frame_is_no_blame(self):
        from dalle_tpu.swarm.allreduce import _parse
        idents, group = self._group()
        chunk = np.arange(8, dtype=np.float32)
        wire = compression.compress(chunk, compression.NONE)
        _, frame = self._frame(idents[0], group, wire,
                               compression.NONE, 8)
        assert _parse(frame, group, [(0, 8)], b"ctx")[0] == "ok"
        # one flipped payload byte (the chaos corrupt fault): the
        # signature no longer verifies — unattributable, never "bad"
        damaged = bytearray(frame)
        damaged[-1] ^= 0x40
        assert _parse(bytes(damaged), group, [(0, 8)], b"ctx") is None
        # truncated tail: same verdict
        assert _parse(frame[:-3], group, [(0, 8)], b"ctx") is None

    def test_signed_garbage_convicts_the_real_sender(self):
        from dalle_tpu.swarm.allreduce import _parse
        idents, group = self._group()
        # authenticated misbehavior: a correctly signed frame whose
        # signed geometry disagrees with the agreed part chunking
        sender, frame = self._frame(idents[1], group, b"\0" * 32,
                                    compression.NONE, 8, ci=0, nc=3)
        status, blamed = _parse(frame, group, [(0, 8)], b"ctx")[:2]
        assert (status, blamed) == ("bad", sender)
        # ...and signed undecodable codec bytes
        sender, frame = self._frame(idents[1], group, b"junk",
                                    compression.UNIFORM8BIT, 8)
        status, blamed = _parse(frame, group, [(0, 8)], b"ctx")[:2]
        assert (status, blamed) == ("bad", sender)


# -- real-socket integration ----------------------------------------------

def _det_swarm(n, base=101):
    """Loopback peers with deterministic identities (test_device_codec
    idiom): part ownership follows peer-id sort order and chaos rolls
    hash the peer id, so runs are value-comparable and fault placement
    is reproducible."""
    from dalle_tpu.swarm.identity import Ed25519PrivateKey
    nodes = []
    for i in range(n):
        peers = [nodes[0].visible_address] if nodes else []
        ident = Identity(Ed25519PrivateKey.from_private_bytes(
            bytes([base + i]) * 32))
        nodes.append(DHT(initial_peers=peers, identity=ident,
                         rpc_timeout=2.0))
    return nodes


def _run_threads(fns, timeout=60):
    results = [None] * len(fns)
    errors = []

    def wrap(i, fn):
        try:
            results[i] = fn()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i, fn))
               for i, fn in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    if errors:
        raise errors[0]
    return results


_INERT_ALL_OPS = FaultPlan(rules=(FaultRule(),))  # matches all, does nothing


class TestBitTransparency:
    def test_wrapped_round_is_byte_identical_to_raw(self):
        """The full protocol stack (matchmaking + chunked u8 allreduce)
        run twice with the same deterministic identities and tensors —
        once raw, once through ChaosDHT with a match-everything inert
        rule — must produce byte-identical averages. This is the
        'zero behavior change when disabled' pin for every layer above
        the transport seam."""
        rng = np.random.RandomState(17)
        tensors = [[rng.randn(2048).astype(np.float32)] for _ in range(2)]

        def round_once(wrap):
            nodes = _det_swarm(2)
            dhts = [ChaosDHT(n, _INERT_ALL_OPS) if wrap else n
                    for n in nodes]
            try:
                def peer(i):
                    g = make_group(dhts[i], "par", epoch=0, weight=1.0,
                                   matchmaking_time=3.0, min_group_size=2)
                    assert g is not None and g.size == 2
                    return run_allreduce(
                        dhts[i], g, "par", 0, tensors[i], weight=1.0,
                        allreduce_timeout=10.0,
                        codec=compression.UNIFORM8BIT, chunk_elems=512)
                return _run_threads([lambda i=i: peer(i)
                                     for i in range(2)])
            finally:
                for n in nodes:
                    n.shutdown()

        raw = round_once(wrap=False)
        chaos = round_once(wrap=True)
        for r, c in zip(raw, chaos):
            np.testing.assert_array_equal(r[0], c[0])


class TestCorruptSenderDegradesGracefully:
    def test_round_completes_offender_renormalized_and_struck(self):
        """Acceptance pin: one peer whose every data-plane send is
        corrupted. The round must complete, honest parts must average
        over the honest contributors only (the offender's weight
        renormalized out), the report must name the offender, and the
        health ledger must record the strike."""
        nodes = _det_swarm(3, base=131)
        pids = [n.peer_id for n in nodes]
        # corrupt the peer owning the LAST part so the two honest peers
        # are deterministic part owners; any choice works, this one
        # keeps the assertions simple
        bad_i = pids.index(max(pids))
        honest = [i for i in range(3) if i != bad_i]
        plan = FaultPlan(seed=9, rules=(FaultRule(ops=("send",),
                                                  corrupt=1.0),))
        dhts = list(nodes)
        dhts[bad_i] = ChaosDHT(nodes[bad_i], plan)
        rng = np.random.RandomState(23)
        tensors = [[rng.randn(300).astype(np.float32)] for _ in range(3)]
        reports = [dict() for _ in range(3)]
        ledgers = [PeerHealthLedger() for _ in range(3)]

        def peer(i):
            g = make_group(dhts[i], "cor", epoch=0, weight=1.0,
                           matchmaking_time=3.0, min_group_size=3)
            assert g is not None and g.size == 3
            return g, run_allreduce(
                dhts[i], g, "cor", 0, tensors[i], weight=1.0,
                allreduce_timeout=8.0, sender_timeout=1.5,
                codec=compression.NONE, report=reports[i],
                ledger=ledgers[i])

        t0 = time.monotonic()
        try:
            results = _run_threads([lambda i=i: peer(i) for i in range(3)])
        finally:
            for n in nodes:
                n.shutdown()
        assert time.monotonic() - t0 < 25  # degraded, never wedged

        group = results[honest[0]][0]
        member_ids = [m.peer_id for m in group.members]
        flats = [flatten_tensors(t) for t in tensors]
        slices = _part_slices(flats[0].size, 3)
        honest_avg = (flats[honest[0]] + flats[honest[1]]) / 2
        for i in honest:
            blamed = (set(reports[i]["corrupt_senders"])
                      | set(reports[i]["timeout_senders"]))
            assert pids[bad_i] in blamed, reports[i]
            assert not reports[i]["complete"]
            # the ledger carries the ban across rounds
            assert ledgers[i].score(pids[bad_i]) > 0
            # this peer's own part: averaged over the two honest
            # contributors exactly — the offender's weight is gone
            my_part = member_ids.index(pids[i])
            lo, hi = slices[my_part]
            got = flatten_tensors(results[i][1])
            np.testing.assert_allclose(got[lo:hi], honest_avg[lo:hi],
                                       rtol=1e-5, atol=1e-6)


class TestLeaderDeathWindow:
    def _announce(self, node, key, weight=1.0):
        node.store(key, node.peer_id,
                   {"addr": node.reachable_address, "weight": float(weight),
                    "kx": node.kx.public_bytes},
                   expiration_time=get_dht_time() + 120)

    def test_followers_fall_back_within_deadline(self):
        """Satellite pin: the leader announces a group then dies before
        confirming. Followers must come back with a usable group within
        their own bounded window (confirm_wait, not K x confirm_wait),
        agree with each other, and strike the no-show leader."""
        idents_nodes = _det_swarm(3, base=151)
        pids = [n.peer_id for n in idents_nodes]
        leader_i = pids.index(min(pids))  # choose_leader picks lowest id
        followers = [i for i in range(3) if i != leader_i]
        leader = idents_nodes[leader_i]
        key = "ld_matchmaking.e0"
        self._announce(leader, key)
        time.sleep(0.4)                  # let the record replicate
        leader.shutdown()                # dies before confirming
        ledgers = {i: PeerHealthLedger() for i in followers}

        def follower(i):
            return make_group(idents_nodes[i], "ld", epoch=0, weight=1.0,
                              matchmaking_time=3.0, min_group_size=3,
                              ledger=ledgers[i])

        t0 = time.monotonic()
        try:
            groups = _run_threads([lambda i=i: follower(i)
                                   for i in followers])
        finally:
            for i in followers:
                idents_nodes[i].shutdown()
        elapsed = time.monotonic() - t0
        # matchmaking window + one confirm_wait + wire slack — NOT a
        # wedged epoch
        assert elapsed < 12, f"followers took {elapsed:.1f}s"
        assert all(g is not None for g in groups)
        assert len({g.group_hash for g in groups}) == 1
        assert all(g.size == 3 for g in groups)  # roster includes the dead
        for i in followers:
            assert ledgers[i].score(pids[leader_i]) > 0  # confirm-timeout

    def test_penalized_peer_dropped_from_candidates(self):
        """Repeat offenders are down-ranked: a peer the local ledger
        penalizes disappears from this peer's matchmaking view until the
        strikes decay."""
        nodes = _det_swarm(2, base=171)
        key = "dr_matchmaking.e0"
        self._announce(nodes[0], key)
        time.sleep(0.3)
        led = PeerHealthLedger(penalty_threshold=3.0)
        for _ in range(2):
            led.strike(nodes[0].peer_id, "corrupt-chunk")  # 4.0 > 3.0
        try:
            g = make_group(nodes[1], "dr", epoch=0, weight=1.0,
                           matchmaking_time=1.5, min_group_size=1,
                           ledger=led)
            assert g is not None and g.size == 1  # offender filtered out
            # decay rehabilitates: with strikes aged out the same view
            # admits the peer again
            led.advance_epoch(led.ttl_epochs + 1)
            g2 = make_group(nodes[1], "dr", epoch=0, weight=1.0,
                            matchmaking_time=1.5, min_group_size=1,
                            ledger=led)
            assert g2 is not None and g2.size == 2
        finally:
            for n in nodes:
                n.shutdown()


class TestStateTransferFailover:
    def test_client_retries_a_different_server(self):
        """Satellite pin: the freshest advertised server goes dark
        mid-stream (its frames vanish); the client must abandon it on a
        bounded per-attempt budget and complete from a DIFFERENT
        advertised server — not burn the whole deadline on the corpse."""
        nodes = _det_swarm(3, base=181)
        black_hole = ChaosDHT(nodes[0], FaultPlan(
            seed=1, rules=(FaultRule(ops=("send",), drop=1.0),)))
        arrays_a = [np.full((64,), 9.0, np.float32)]
        arrays_b = [np.full((64,), 4.0, np.float32)]
        # A advertises the fresher epoch, so the client tries it first
        srv_a = StateServer(black_hole, "fo", lambda: (9, arrays_a),
                            announce_period=0.2)
        srv_b = StateServer(nodes[1], "fo", lambda: (4, arrays_b),
                            announce_period=0.2)
        srv_a.start(), srv_b.start()
        try:
            deadline = time.monotonic() + 30
            result = None
            while result is None and time.monotonic() < deadline:
                result = load_state_from_peers(nodes[2], "fo",
                                               timeout=10.0)
            assert result is not None
            epoch, got = result
            assert epoch == 4                     # the live server won
            np.testing.assert_allclose(got[0], arrays_b[0], atol=1e-3)
        finally:
            srv_a.stop(), srv_b.stop()
            for n in nodes:
                n.shutdown()


# -- churn soak -----------------------------------------------------------

class TestChurnSoak:
    def test_schedule_is_seed_deterministic(self):
        from scripts.churn_soak import build_schedule
        a = build_schedule(seed=42, n_peers=5, epochs=8, kills=2, joins=1)
        b = build_schedule(seed=42, n_peers=5, epochs=8, kills=2, joins=1)
        c = build_schedule(seed=43, n_peers=5, epochs=8, kills=2, joins=1)
        assert a == b
        assert a != c
        assert len(a["kills"]) == 2 and len(a["joins"]) == 1
        assert a["partition"]["end_s"] > a["partition"]["start_s"]

    def test_fast_soak(self, tmp_path):
        """Tier-1 churn soak: 3 peers + 1 join, 1 crash-at-epoch kill,
        a short partition window — liveness (every survivor reaches the
        target epoch, no wedge, no leaked threads) and convergence
        (identical state fingerprints) asserted by the script itself."""
        from scripts.churn_soak import main
        out = tmp_path / "CHURN_SOAK.json"
        rc = main(["--peers", "3", "--epochs", "4", "--joins", "1",
                   "--kills", "1", "--seed", "7",
                   "--matchmaking-time", "1.2", "--allreduce-timeout", "5",
                   "--deadline", "120", "--out", str(out)])
        assert rc == 0, f"churn soak reported a violation (see {out})"
        import json
        report = json.loads(out.read_text())
        assert report["pass"] is True
        assert report["violations"] == []
        fps = [p["fingerprint"] for p in report["peers"]
               if p["survivor"]]
        assert len(set(fps)) == 1 and len(fps) >= 3  # 2 survivors + joiner

    def test_fast_soak_pipelined(self, tmp_path):
        """Tier-1 churn soak on the r19 PIPELINED wire (pipeline_hops):
        the same liveness + convergence oracles must stay green when
        parts complete out of order — kills and a join included."""
        from scripts.churn_soak import main
        out = tmp_path / "CHURN_SOAK.json"
        rc = main(["--peers", "3", "--epochs", "4", "--joins", "1",
                   "--kills", "1", "--seed", "9",
                   "--matchmaking-time", "1.2", "--allreduce-timeout", "5",
                   "--deadline", "120", "--pipeline", "--out", str(out)])
        assert rc == 0, f"pipelined churn soak violation (see {out})"
        import json
        report = json.loads(out.read_text())
        assert report["pass"] is True
        assert report["violations"] == []
        assert report["params"]["pipeline"] is True
        fps = [p["fingerprint"] for p in report["peers"]
               if p["survivor"]]
        assert len(set(fps)) == 1 and len(fps) >= 3

    @pytest.mark.slow
    def test_full_soak(self, tmp_path):
        """The full-size soak (>=5 peers, kills + join + partition) —
        slow-marked; scripts/churn_soak.py with defaults is the same
        gate from the command line."""
        from scripts.churn_soak import main
        out = tmp_path / "CHURN_SOAK.json"
        rc = main(["--peers", "5", "--epochs", "6", "--joins", "1",
                   "--kills", "2", "--seed", "11",
                   "--deadline", "420", "--out", str(out)])
        assert rc == 0


def test_fingerprint_helper_matches_sha256():
    from scripts.churn_soak import fingerprint
    x = np.arange(8, dtype=np.float32)
    assert fingerprint(x) == hashlib.sha256(x.tobytes()).hexdigest()[:16]
