"""Multi-host slices: one swarm peer per pod slice (parallel/multihost.py).

The north-star deployment: a whole pod slice presents as ONE volunteer —
process 0 speaks the swarm protocol, followers receive decisions/averages
via broadcasts (SURVEY.md §5 comm backend; the reference's analogue is the
single host process of a TPU-VM talking to hivemind while 8 cores
all-reduce locally, run_trainer_tpu.py:78-91).

The integration test runs TWO real JAX processes joined through
``jax.distributed.initialize`` on the CPU backend and checks both end a
swarm epoch with byte-identical parameters.
"""

import json
import os
import re
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent

_CHILD = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]; dht_port = sys.argv[3]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=pid)
import jax.numpy as jnp
import numpy as np
import optax

from dalle_tpu.config import CollabConfig
from dalle_tpu.parallel.multihost import SliceRole
from dalle_tpu.swarm.optimizer import CollaborativeOptimizer
from dalle_tpu.training.steps import TrainState, make_apply_step

role = SliceRole()
assert role.n_processes == 2
dht = None
if role.swarm_enabled:
    from dalle_tpu.swarm.dht import DHT
    from dalle_tpu.swarm.identity import Identity
    dht = DHT(host="127.0.0.1", port=int(dht_port),
              identity=Identity.generate())

cfg = CollabConfig(run_id="mh", target_batch_size=16,
                   matchmaking_time=1.0, allreduce_timeout=10.0,
                   averaging_timeout=20.0, average_state_every=0,
                   grad_compression="none")
tx = optax.sgd(0.1)
params = {"w": jnp.ones((8, 4), jnp.float32)}
state = TrainState.create(params, tx)
opt = CollaborativeOptimizer(dht, cfg, state, jax.jit(make_apply_step(tx)),
                             serve_state=False, matchmaking_min_group=1,
                             role=role)
if role.swarm_enabled:
    opt.tracker.min_refresh_period = 0.05

grads = {"w": jnp.full((8, 4), 2.0, jnp.float32)}
steps = 0
while opt.local_epoch < 1 and steps < 50:
    opt.step(grads, batch_size=8)
    steps += 1

w = np.asarray(opt.state.params["w"])
print(json.dumps({"pid": pid, "epoch": opt.local_epoch,
                  "steps": steps,
                  "w0": float(w.flat[0]),
                  "digest": __import__("hashlib").sha256(
                      w.tobytes()).hexdigest()}))
if dht is not None:
    dht.shutdown()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_slice_applies_identical_updates():
    env = dict(os.environ)
    # one cpu device per process; no TPU relay dialing
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", "")).strip()
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    port, dht_port = _free_port(), _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(pid), str(port),
             str(dht_port)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
            assert p.returncode == 0, out[-3000:]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise AssertionError(
            "multihost children hung:\n" +
            "\n".join(o[-2000:] for o in outs))

    results = []
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        results.append(json.loads(line))
    by_pid = {r["pid"]: r for r in results}
    assert by_pid[0]["epoch"] == by_pid[1]["epoch"] == 1
    # both processes applied the identical update: w = 1 - 0.1*2 = 0.8
    assert abs(by_pid[0]["w0"] - 0.8) < 1e-5
    assert by_pid[0]["digest"] == by_pid[1]["digest"]
    # followers and coordinator ran the same number of lockstep steps
    assert by_pid[0]["steps"] == by_pid[1]["steps"]
