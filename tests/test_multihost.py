"""Multi-host slices: one swarm peer per pod slice (parallel/multihost.py).

The north-star deployment: a whole pod slice presents as ONE volunteer —
process 0 speaks the swarm protocol, followers receive decisions/averages
via broadcasts (SURVEY.md §5 comm backend; the reference's analogue is the
single host process of a TPU-VM talking to hivemind while 8 cores
all-reduce locally, run_trainer_tpu.py:78-91).

The integration test runs TWO real JAX processes joined through
``jax.distributed.initialize`` on the CPU backend and checks both end a
swarm epoch with byte-identical parameters.
"""

import json
import os
import re
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def _cpu_multiprocess_collectives() -> str:
    """The configured CPU collectives implementation ("none" when
    multiprocess CPU computations are unsupported).

    Every child below pins ``JAX_PLATFORMS=cpu``, so what decides
    whether these tests CAN pass is whether the CPU client gets a
    collectives backend (gloo/mpi). jaxlib ships gloo, but the
    ``jax_cpu_collectives_implementation`` flag defaults to "none" — and
    with "none" the very first cross-process computation raises
    ``XlaRuntimeError: Multiprocess computations aren't implemented on
    the CPU backend``, which makes all three subprocess tests
    guaranteed failures (each burning its full matchmaking/averaging
    timeout). Children inherit our environment, so reading the parent's
    flag is faithful: export ``JAX_CPU_COLLECTIVES_IMPLEMENTATION=gloo``
    (or ``jax.config.update`` in a conftest) and the skip lifts.
    """
    try:
        from jax._src import xla_bridge
        return xla_bridge.CPU_COLLECTIVES_IMPLEMENTATION.value or "none"
    except Exception:
        return "none"


pytestmark = pytest.mark.skipif(
    _cpu_multiprocess_collectives() == "none",
    reason="Multiprocess computations aren't implemented on the CPU "
           "backend: jax_cpu_collectives_implementation is 'none' (set "
           "JAX_CPU_COLLECTIVES_IMPLEMENTATION=gloo to run these)")

_CHILD = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]; dht_port = sys.argv[3]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=pid)
import jax.numpy as jnp
import numpy as np
import optax

from dalle_tpu.config import CollabConfig
from dalle_tpu.parallel.multihost import SliceRole
from dalle_tpu.swarm.optimizer import CollaborativeOptimizer
from dalle_tpu.training.steps import TrainState, make_apply_step

role = SliceRole()
assert role.n_processes == 2
dht = None
if role.swarm_enabled:
    from dalle_tpu.swarm.dht import DHT
    from dalle_tpu.swarm.identity import Identity
    dht = DHT(host="127.0.0.1", port=int(dht_port),
              identity=Identity.generate())

cfg = CollabConfig(run_id="mh", target_batch_size=16,
                   matchmaking_time=1.0, allreduce_timeout=10.0,
                   averaging_timeout=20.0, average_state_every=0,
                   grad_compression="none")
tx = optax.sgd(0.1)
params = {"w": jnp.ones((8, 4), jnp.float32)}
state = TrainState.create(params, tx)
opt = CollaborativeOptimizer(dht, cfg, state, jax.jit(make_apply_step(tx)),
                             serve_state=False, matchmaking_min_group=1,
                             role=role)
if role.swarm_enabled:
    opt.tracker.min_refresh_period = 0.05

grads = {"w": jnp.full((8, 4), 2.0, jnp.float32)}
steps = 0
while opt.local_epoch < 1 and steps < 50:
    opt.step(grads, batch_size=8)
    steps += 1

w = np.asarray(opt.state.params["w"])
print(json.dumps({"pid": pid, "epoch": opt.local_epoch,
                  "steps": steps,
                  "w0": float(w.flat[0]),
                  "digest": __import__("hashlib").sha256(
                      w.tobytes()).hexdigest()}))
opt.shutdown()  # drain any background round BEFORE the native node dies
if dht is not None:
    dht.shutdown()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_slice_applies_identical_updates():
    env = dict(os.environ)
    # one cpu device per process; no TPU relay dialing
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", "")).strip()
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    port, dht_port = _free_port(), _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(pid), str(port),
             str(dht_port)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
            assert p.returncode == 0, out[-3000:]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise AssertionError(
            "multihost children hung:\n" +
            "\n".join(o[-2000:] for o in outs))

    results = []
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        results.append(json.loads(line))
    by_pid = {r["pid"]: r for r in results}
    assert by_pid[0]["epoch"] == by_pid[1]["epoch"] == 1
    # both processes applied the identical update: w = 1 - 0.1*2 = 0.8
    assert abs(by_pid[0]["w0"] - 0.8) < 1e-5
    assert by_pid[0]["digest"] == by_pid[1]["digest"]
    # followers and coordinator ran the same number of lockstep steps
    assert by_pid[0]["steps"] == by_pid[1]["steps"]


_SLICE_CHILD = r"""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]; dht_port = sys.argv[3]
compression = sys.argv[4]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=pid)
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from dalle_tpu.config import CollabConfig
from dalle_tpu.parallel.multihost import SliceRole
from dalle_tpu.swarm.optimizer import CollaborativeOptimizer
from dalle_tpu.training.steps import TrainState, make_apply_step

role = SliceRole()
dht = None
if role.swarm_enabled:
    from dalle_tpu.swarm.dht import DHT
    from dalle_tpu.swarm.identity import Identity
    dht = DHT(host="127.0.0.1", port=int(dht_port),
              identity=Identity.generate())

cfg = CollabConfig(run_id="mhs", target_batch_size=32,
                   matchmaking_time=3.0, allreduce_timeout=15.0,
                   averaging_timeout=30.0, average_state_every=0,
                   grad_compression=compression, powersgd_rank=2,
                   encrypt_data_plane=False)
# state sharded ACROSS the two processes (1 CPU device each) — the
# ADVICE-r2 crash scenario: np.asarray on such arrays raises
mesh = jax.make_mesh((2,), ("fsdp",))
shard = NamedSharding(mesh, P("fsdp"))
rep = NamedSharding(mesh, P())
tx = optax.sgd(0.1)
params = {"w": jax.device_put(np.ones((64, 32), np.float32), shard),
          "b": jax.device_put(np.zeros((32,), np.float32), rep)}
state = TrainState.create(params, tx)
opt = CollaborativeOptimizer(dht, cfg, state, jax.jit(make_apply_step(tx)),
                             serve_state=False, matchmaking_min_group=2,
                             role=role)
if role.swarm_enabled:
    opt.tracker.min_refresh_period = 0.05

grads = {"w": jax.device_put(np.full((64, 32), 2.0, np.float32), shard),
         "b": jax.device_put(np.full((32,), 1.0, np.float32), rep)}
steps = 0
deadline = time.monotonic() + 120
while opt.local_epoch < 1 and time.monotonic() < deadline:
    opt.step(grads, batch_size=8)
    steps += 1
from dalle_tpu.parallel.multihost import host_global
w, b = host_global([opt.state.params["w"], opt.state.params["b"]])
print(json.dumps({"pid": pid, "epoch": opt.local_epoch, "steps": steps,
                  "w0": float(w.flat[0]), "b0": float(b.flat[0]),
                  "digest": __import__("hashlib").sha256(
                      w.tobytes() + b.tobytes()).hexdigest()}))
opt.shutdown()  # drain any background round BEFORE the native node dies
if dht is not None:
    dht.shutdown()
"""

_PLAIN_PEER_CHILD = r"""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
dht_port = sys.argv[1]; compression = sys.argv[2]
import jax.numpy as jnp
import numpy as np
import optax

from dalle_tpu.config import CollabConfig
from dalle_tpu.swarm.dht import DHT
from dalle_tpu.swarm.identity import Identity
from dalle_tpu.swarm.optimizer import CollaborativeOptimizer
from dalle_tpu.training.steps import TrainState, make_apply_step

dht = DHT(host="127.0.0.1", port=0, identity=Identity.generate())
deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    if dht.bootstrap(f"127.0.0.1:{dht_port}"):
        break
    time.sleep(0.25)
else:
    raise SystemExit("could not bootstrap to the slice coordinator")

cfg = CollabConfig(run_id="mhs", target_batch_size=32,
                   matchmaking_time=3.0, allreduce_timeout=15.0,
                   averaging_timeout=30.0, average_state_every=0,
                   grad_compression=compression, powersgd_rank=2,
                   encrypt_data_plane=False)
tx = optax.sgd(0.1)
params = {"w": jnp.ones((64, 32), jnp.float32),
          "b": jnp.zeros((32,), jnp.float32)}
state = TrainState.create(params, tx)
opt = CollaborativeOptimizer(dht, cfg, state, jax.jit(make_apply_step(tx)),
                             serve_state=False, matchmaking_min_group=2)
opt.tracker.min_refresh_period = 0.05

grads = {"w": jnp.full((64, 32), 4.0, jnp.float32),
         "b": jnp.full((32,), 3.0, jnp.float32)}
steps = 0
deadline = time.monotonic() + 120
while opt.local_epoch < 1 and time.monotonic() < deadline:
    opt.step(grads, batch_size=8)
    steps += 1
w = np.asarray(opt.state.params["w"])
b = np.asarray(opt.state.params["b"])
print(json.dumps({"pid": "peer", "epoch": opt.local_epoch, "steps": steps,
                  "w0": float(w.flat[0]), "b0": float(b.flat[0])}))
# overlapped rounds (delay_optimizer_step) may still be on the wire:
# the optimizer MUST shut down before the native DHT node is destroyed
# (task.shutdown's ordering) or the round thread touches freed memory
opt.shutdown()
dht.shutdown()
"""


def _run_sharded_slice_with_peer(compression: str):
    env = dict(os.environ)
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", "")).strip()
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    port, dht_port = _free_port(), _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _SLICE_CHILD, str(pid), str(port),
             str(dht_port), compression],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]
    procs.append(subprocess.Popen(
        [sys.executable, "-c", _PLAIN_PEER_CHILD, str(dht_port),
         compression],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
            assert p.returncode == 0, out[-3000:]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise AssertionError(
            "sharded-slice children hung:\n" +
            "\n".join(o[-2000:] for o in outs))

    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        r = json.loads(line)
        results[r["pid"]] = r
    # everyone finished the epoch
    assert results[0]["epoch"] == results[1]["epoch"] == 1
    assert results["peer"]["epoch"] == 1
    # the sample-weighted mean of the two peers' constant grads lies
    # strictly between them (w in [2,4], b in [1,3]; the free-running
    # plain peer usually accumulates more samples than the lockstep
    # slice, so the exact point depends on timing), and w/b must tell a
    # CONSISTENT story: b's per-sample grad is exactly w's minus 1
    for r in (results[0], results[1], results["peer"]):
        w_avg = (1.0 - r["w0"]) * 10.0
        b_avg = -r["b0"] * 10.0
        assert 2.0 - 1e-3 <= w_avg <= 4.0 + 1e-3, r
        assert abs(b_avg - (w_avg - 1.0)) < 5e-3, r
    # every participant applied the same averaged gradients
    assert abs(results[0]["w0"] - results["peer"]["w0"]) < 1e-4
    # the slice's two processes are byte-identical
    assert results[0]["digest"] == results[1]["digest"]


def test_sharded_slice_cotrains_with_plain_peer_powersgd():
    """ADVICE r2 (medium): a slice whose state/grads are sharded ACROSS
    processes must survive the global step — the PowerSGD device phases
    run as SPMD collectives on every process, factors are all-gathered
    for the wire, and the completeness flag is broadcast."""
    _run_sharded_slice_with_peer("power_sgd")


def test_sharded_slice_cotrains_with_plain_peer_allreduce():
    """Same scenario through the plain all-reduce path: the sharded
    gradient pull is a lockstep all-gather and the averaged result is
    broadcast to followers."""
    _run_sharded_slice_with_peer("none")
