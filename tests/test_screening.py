"""Byzantine defense layer (swarm/screening.py + health.py receipts +
chaos.py byzantine ops): the content trust model above signatures.

Four layers, mirroring CHAOS.md "Defense in depth":

- the GradientScreen's pure math: norm/cosine boundaries, leave-one-out
  correctness, the iterative (masking-resistant) drop order, the
  small-swarm skip, the max_drop_frac ceiling, and the honest-
  heterogeneity false-positive pin;
- signed strike receipts: sign/verify/dedup, bounded per-issuer and
  total remote influence (no veto: remote receipts alone can never
  convict), decay;
- real-socket integration: a sign-flip attacker screened at every
  honest part owner with bit-exact honest averages, the frame-weight
  clamp, the screening-disabled transparency pin, and the 2-peer
  unattributability rule;
- the byzantine soak gate (scripts/churn_soak.py --byzantine): fast
  variant in tier-1, full soak slow-marked (pytest.ini).
"""

import threading
import time

import numpy as np
import pytest

from dalle_tpu.swarm import DHT, Identity
from dalle_tpu.swarm import compression
from dalle_tpu.swarm.allreduce import (_part_slices, flatten_tensors,
                                       run_allreduce)
from dalle_tpu.swarm.chaos import ByzantineOp, ChaosDHT, FaultPlan
from dalle_tpu.swarm.dht import ValueWithExpiration
from dalle_tpu.swarm.health import (GOSSIP_REASONS, PeerHealthLedger,
                                    StrikeGossip, make_receipt,
                                    open_receipt)
from dalle_tpu.swarm.matchmaking import make_group
from dalle_tpu.swarm.screening import GradientScreen, ScreenPolicy


G = np.arange(1, 17, dtype=np.float32)  # a generic honest segment


def contribs(*segs, weights=None):
    w = weights or [1.0] * len(segs)
    return {i: (w[i], np.asarray(s, np.float32))
            for i, s in enumerate(segs)}


# -- the screen's pure math ------------------------------------------------

class TestScreenPolicy:
    def test_validation(self):
        with pytest.raises(ValueError, match="min_senders"):
            ScreenPolicy(min_senders=2)
        with pytest.raises(ValueError, match="max_drop_frac"):
            ScreenPolicy(max_drop_frac=1.0)
        with pytest.raises(ValueError, match="norm_tolerance"):
            ScreenPolicy(norm_tolerance=1.0)
        with pytest.raises(ValueError, match="cosine_floor"):
            ScreenPolicy(cosine_floor=-2.0)


class TestGradientScreen:
    def test_sign_flip_dropped_by_cosine(self):
        v = GradientScreen().screen(contribs(G, G, G, G, -G))
        assert list(v.dropped) == [4]
        assert v.dropped[4].startswith("cosine")

    def test_scale_dropped_by_norm(self):
        v = GradientScreen().screen(contribs(G, G, G, G, 100 * G))
        assert list(v.dropped) == [4]
        assert v.dropped[4].startswith("norm-ratio")

    def test_loud_outlier_does_not_mask_quiet_one(self):
        """The masking attack on one-shot outlier tests: a -10x-scaled
        contribution drags the leave-one-out mean so far that a plain
        sign flip looks AGREEING (cos(-g, mean incl -10g) = +1). The
        iterative screen drops the loud one first, re-measures, then
        catches the quiet one."""
        v = GradientScreen().screen(contribs(G, G, G, -G, -10 * G))
        assert set(v.dropped) == {3, 4}
        assert v.dropped[4].startswith("norm-ratio")
        assert v.dropped[3].startswith("cosine")

    def test_leave_one_out_math(self):
        """stats = (norm/median, cos(v_i, loo mean)) — verified by hand
        on survivors after a no-drop screen."""
        a = np.array([2.0, 0.0], np.float32)
        b = np.array([0.0, 2.0], np.float32)
        c = np.array([2.0, 2.0], np.float32)
        d = np.array([1.0, 1.0], np.float32)
        v = GradientScreen().screen(contribs(a, b, c, d))
        assert not v.dropped
        # sender 3: loo mean = (a+b+c)/3 = (4/3, 4/3); cos(d, loo) = 1
        assert v.stats[3][1] == pytest.approx(1.0)
        # sender 0: loo mean = (b+c+d)/3 = (1, 5/3)
        loo = np.array([1.0, 5 / 3])
        want = float(a @ loo / (np.linalg.norm(a) * np.linalg.norm(loo)))
        assert v.stats[0][1] == pytest.approx(want)
        # norms: |a|=|b|=2, |c|=2sqrt2, |d|=sqrt2 -> median 2
        assert v.stats[2][0] == pytest.approx(np.sqrt(2))

    def test_norm_boundary_is_strict(self):
        pol = ScreenPolicy(norm_tolerance=4.0)
        # ratio exactly 4.0: NOT an outlier (strict >)
        v = GradientScreen(pol).screen(contribs(G, G, G, 4 * G))
        assert not v.dropped
        v = GradientScreen(pol).screen(contribs(G, G, G, 4.5 * G))
        assert list(v.dropped) == [3]

    def test_cosine_boundary_is_strict(self):
        # orthogonal vector: cos = 0, floor = 0.0 -> not dropped
        pol = ScreenPolicy(cosine_floor=0.0)
        ortho = np.zeros_like(G)
        ortho[0], ortho[1] = G[1], -G[0]  # perpendicular to G in 2 dims
        assert float(ortho @ G) == 0.0
        v = GradientScreen(pol).screen(contribs(G, G, G, ortho))
        assert not v.dropped
        v = GradientScreen(pol).screen(contribs(G, G, G, -G))
        assert list(v.dropped) == [3]

    def test_small_swarm_skipped_nonfinite_still_dropped(self):
        """Below min_senders the outlier screen must not run (with 2-3
        senders a leave-one-out consensus is one peer's word against
        another's) — but NaN/Inf is poison at any size."""
        v = GradientScreen().screen(contribs(G, -G))
        assert v.skipped and not v.dropped
        v = GradientScreen().screen(contribs(G, G, -G))
        assert v.skipped and not v.dropped
        bad = G.copy()
        bad[3] = np.nan
        v = GradientScreen().screen(contribs(G, bad))
        assert v.skipped and v.dropped == {1: "nonfinite"}

    def test_max_drop_frac_ceiling(self):
        """With outliers beyond the budget, only floor(frac * n) drop —
        the WORST first — so a coordinated minority can never turn the
        screen into a majority-eviction tool."""
        pol = ScreenPolicy(max_drop_frac=0.34)  # floor(0.34 * 6) = 2
        v = GradientScreen(pol).screen(
            contribs(G, G, G, -G, 50 * G, 100 * G))
        assert len(v.dropped) == 2
        assert set(v.dropped) == {4, 5}  # loudest norms outrank the flip

    def test_weight_zero_contributions_ignored(self):
        v = GradientScreen().screen(
            contribs(G, G, G, G, -G, weights=[1, 1, 1, 1, 0]))
        assert not v.dropped  # the flip never reaches the accumulator

    def test_nonfinite_weight_dropped(self):
        """A NaN/Inf WEIGHT poisons total_w exactly like NaN data —
        and NaN slips past a `w <= 0` sign check — so it must be
        dropped at any roster size (the clamp may be disabled)."""
        v = GradientScreen().screen(
            contribs(G, G, G, G, G,
                     weights=[1, 1, 1, 1, float("nan")]))
        assert v.dropped == {4: "nonfinite"}
        v = GradientScreen().screen(
            contribs(G, G, weights=[1, float("inf")]))
        assert v.dropped == {1: "nonfinite"}  # even below min_senders

    def test_zero_vector_is_harmless(self):
        v = GradientScreen().screen(contribs(G, G, G, np.zeros_like(G)))
        assert not v.dropped

    def test_deterministic(self):
        c = contribs(G, G + 1, G - 1, -G, 30 * G)
        a = GradientScreen().screen(c)
        b = GradientScreen().screen(c)
        assert a.dropped == b.dropped and a.stats == b.stats

    def test_honest_heterogeneity_never_screened(self):
        """THE false-positive pin: honest non-IID volunteers — a shared
        signal plus per-peer noise, per-peer norms spread over ~3x, a
        couple of weight-imbalanced peers — must never be screened, for
        any of several draws. A screen that eats honest heterogeneity
        would silently shrink every round's effective batch."""
        screen = GradientScreen()
        for seed in range(10):
            rng = np.random.RandomState(seed)
            signal = rng.randn(256).astype(np.float32)
            c = {}
            for i in range(8):
                scale = rng.uniform(0.5, 1.6)       # batch-size spread
                noise = rng.randn(256).astype(np.float32)
                c[i] = (float(rng.choice([0.5, 1.0, 2.0, 4.0])),
                        (signal * scale
                         + 0.8 * noise).astype(np.float32))
            v = screen.screen(c)
            assert not v.dropped, (seed, v.dropped, v.stats)

    @pytest.mark.parametrize("codec", [compression.UNIFORM8BIT,
                                       compression.UNIFORM4BIT])
    def test_honest_heterogeneity_survives_quantized_wire(self, codec):
        """The r15 re-calibration pin: what the screen actually sees
        on a quantized run is the codec round-trip of (possibly
        EF-compensated) segments — quantization noise + a bounded
        residual must not push honest non-IID volunteers over the
        pinned thresholds, at u8 OR u4. EF residuals are bounded by
        one quantization step, so compensation is modeled as one prior
        round's error added in."""
        screen = GradientScreen()
        for seed in range(10):
            rng = np.random.RandomState(seed)
            signal = rng.randn(256).astype(np.float32)
            c = {}
            for i in range(8):
                scale = rng.uniform(0.5, 1.6)
                noise = rng.randn(256).astype(np.float32)
                seg = (signal * scale + 0.8 * noise).astype(np.float32)
                # one EF step: residual of a previous round's quantize
                prev = (signal * scale * 0.9
                        + 0.8 * rng.randn(256)).astype(np.float32)
                resid = prev - compression.decompress(
                    compression.compress(prev, codec), codec, prev.size)
                comp = seg + resid
                wire = compression.decompress(
                    compression.compress(comp, codec), codec, comp.size)
                c[i] = (float(rng.choice([0.5, 1.0, 2.0, 4.0])), wire)
            v = screen.screen(c)
            assert not v.dropped, (codec, seed, v.dropped, v.stats)

    def test_fixed_order_statistics_are_build_independent(self):
        """The CHAOS.md determinism-gap fix: the screen's norm/dot
        reductions spell out their summation order in code (row-wise
        elementwise adds + an exactly-rounded fsum combine), so the
        result is a pure function of the input BYTES — never of the
        numpy build's SIMD width or BLAS. Pinned three ways: inputs
        inside one lane block are EXACTLY rounded (equal math.fsum
        over any permutation — the ulp-boundary case); a multi-block
        cancellation-heavy input pins a golden bit pattern (any
        order change flips it); and the statistics must not regress
        to backend reductions (np.sum disagrees on this input)."""
        import math
        from dalle_tpu.swarm.screening import (_fixed_order_sum,
                                               _fsum_dot, _fsum_norm)
        rng = np.random.RandomState(0)
        # (1) <= one lane: exactly rounded, permutation-invariant
        small = np.concatenate([
            rng.randn(1000) * 1e6, rng.randn(1000) * 1e-3,
            -rng.randn(1000) * 1e6]).astype(np.float64)
        assert _fixed_order_sum(small) == math.fsum(small.tolist())
        for _ in range(3):
            p = rng.permutation(small.size)
            assert _fixed_order_sum(small[p]) == \
                math.fsum(small[p].tolist())
        # (2) multi-block: the spelled-out order IS the spec — a
        # checked-in golden bit pattern catches any reordering (a
        # backend-reduction regression, a lane-width change, a
        # combine-order edit) on the spot
        big = np.concatenate([
            rng.randn(5000) * 1e6, rng.randn(5000) * 1e-3,
            -rng.randn(5000) * 1e6]).astype(np.float64)
        assert np.float64(_fixed_order_sum(big)).tobytes().hex() == \
            "191bdb2769b4a5c1"
        # (3) the deterministic norms/dots flow through the same path
        other = rng.randn(big.size)
        assert _fsum_norm(big) == math.sqrt(
            _fixed_order_sum(np.square(big)))
        assert _fsum_dot(big, other) == _fixed_order_sum(big * other)


# -- signed strike receipts ------------------------------------------------

class TestReceipts:
    def test_roundtrip_and_issuer_binding(self):
        ident = Identity.generate()
        peer = "cd" * 32
        raw = make_receipt(ident, "runX", peer, "screen-outlier", 7)
        opened = open_receipt(raw, "runX")
        assert opened is not None
        issuer, got_peer, reason, epoch = opened
        import hashlib
        assert issuer == hashlib.sha256(ident.public_bytes).hexdigest()
        assert (got_peer, reason, epoch) == (peer, "screen-outlier", 7)

    def test_tampered_or_cross_run_rejected(self):
        ident = Identity.generate()
        raw = make_receipt(ident, "runX", "cd" * 32, "corrupt-chunk", 1)
        bad = bytearray(raw)
        bad[-1] ^= 0x01
        assert open_receipt(bytes(bad), "runX") is None
        assert open_receipt(raw[:-2], "runX") is None
        # the run prefix is signed context: no cross-swarm replay
        assert open_receipt(raw, "runY") is None

    def test_strict_content(self):
        """Unknown reasons and malformed ids must be rejected — the
        strike plane is attacker-writable, and a verifier must never
        fold a claim it cannot price."""
        ident = Identity.generate()
        assert "made-up-reason" not in GOSSIP_REASONS
        raw = make_receipt(ident, "r", "cd" * 32, "made-up-reason", 1)
        assert open_receipt(raw, "r") is None
        raw = make_receipt(ident, "r", "not-a-peer-id", "corrupt-chunk", 1)
        assert open_receipt(raw, "r") is None
        raw = make_receipt(ident, "r", "cd" * 32, "corrupt-chunk", -1)
        assert open_receipt(raw, "r") is None
        # timeout reasons are unattributable by design: never gossiped,
        # never folded
        raw = make_receipt(ident, "r", "cd" * 32, "reduce-timeout", 1)
        assert open_receipt(raw, "r") is None


class TestLedgerRemoteInfluence:
    def test_per_issuer_cap_no_single_issuer_veto(self):
        led = PeerHealthLedger(max_issuer_influence=1.0,
                               max_remote_influence=2.0)
        for epoch in range(20):  # one issuer flooding receipts
            led.remote_strike("issuer-a", "p1", "screen-outlier", 0)
        assert led.score("p1") == pytest.approx(1.0)
        assert not led.penalized("p1")

    def test_total_remote_cap_below_threshold(self):
        """Remote receipts ALONE can never convict (Sybil issuers mint
        identities for free): the total remote influence cap sits below
        the penalty threshold, so conviction requires local evidence."""
        led = PeerHealthLedger(penalty_threshold=3.0,
                               max_remote_influence=2.0)
        for i in range(10):  # 10 distinct issuers co-signing
            led.remote_strike(f"issuer-{i}", "p1", "screen-outlier", 0)
        assert led.score("p1") == pytest.approx(2.0)
        assert not led.penalized("p1")
        led.strike("p1", "reduce-timeout")  # any local corroboration
        assert led.penalized("p1")

    def test_remote_strikes_decay(self):
        led = PeerHealthLedger(ttl_epochs=2)
        led.remote_strike("i1", "p1", "screen-outlier", 0)
        assert led.score("p1") > 0
        led.advance_epoch(3)
        assert led.score("p1") == 0.0
        assert led.snapshot() == {}

    def test_forward_dated_receipt_clamped_to_local_clock(self):
        """An attacker-issued receipt claiming epoch 10^9 must not
        outlive the decay window: fold clamps to the local epoch."""
        led = PeerHealthLedger(ttl_epochs=2)
        led.advance_epoch(5)
        led.remote_strike("i1", "p1", "screen-outlier", 10 ** 9)
        assert led.score("p1") > 0
        led.advance_epoch(8)  # clamped epoch 5 ages out at 5 + ttl
        assert led.score("p1") == 0.0

    def test_snapshot_merges_both_planes(self):
        led = PeerHealthLedger()
        led.strike("p1", "corrupt-chunk")
        led.remote_strike("i1", "p1", "screen-outlier", 0)
        led.remote_strike("i1", "p2", "screen-outlier", 0)
        snap = led.snapshot()
        assert snap["p1"] == pytest.approx(3.0)  # 2.0 local + 1.0 capped
        assert snap["p2"] == pytest.approx(1.0)
        assert led.remote_score("p1") == pytest.approx(1.0)


class _GossipStub:
    """Record-plane double: every stub shares one store, so receipts
    published by one 'peer' are visible to the others' fold."""

    def __init__(self, shared):
        self.identity = Identity.generate()
        import hashlib
        self.peer_id = hashlib.sha256(
            self.identity.public_bytes).hexdigest()
        self.shared = shared

    def store(self, key, subkey, value, expiration_time):
        self.shared.setdefault(key, {})[subkey] = ValueWithExpiration(
            value, expiration_time)
        return True

    def get(self, key, latest=True):
        return dict(self.shared.get(key, {})) or None


class TestStrikeGossip:
    def _pair(self):
        shared = {}
        a, b = _GossipStub(shared), _GossipStub(shared)
        la, lb = PeerHealthLedger(), PeerHealthLedger()
        return (StrikeGossip(a, la, "g"), la), (StrikeGossip(b, lb, "g"),
                                                lb)

    def test_publish_fold_roundtrip_and_dedup(self):
        (ga, la), (gb, lb) = self._pair()
        offender = "ee" * 32
        la.strike(offender, "screen-outlier")
        assert ga.publish_once() == 1
        assert gb.fold_once() == 1
        assert lb.remote_score(offender) == pytest.approx(1.0)
        # the DHT returns the same record every poll: folding again
        # must not stack influence
        assert gb.fold_once() == 0
        assert lb.remote_score(offender) == pytest.approx(1.0)
        # publishing again with no new events is a no-op
        assert ga.publish_once() == 0

    def test_unattributable_reasons_never_gossip(self):
        (ga, la), (gb, lb) = self._pair()
        la.strike("ee" * 32, "reduce-timeout")
        la.strike("ee" * 32, "gather-timeout")
        la.strike("ee" * 32, "confirm-timeout")
        assert ga.publish_once() == 0  # silence is not evidence

    def test_own_and_self_receipts_not_folded(self):
        (ga, la), (gb, lb) = self._pair()
        # a receipt naming the READER: never folded (no self-conviction
        # by gossip), and a receipt the reader itself issued adds
        # nothing (already a local strike)
        la.strike(gb.dht.peer_id, "screen-outlier")
        ga.publish_once()
        assert gb.fold_once() == 0
        assert lb.score(gb.dht.peer_id) == 0.0
        lb.strike("ee" * 32, "corrupt-chunk")
        gb.publish_once()
        assert gb.fold_once() == 0  # own receipt skipped

    def test_self_strike_events_not_published(self):
        (ga, la), _ = self._pair()
        la.strike(ga.dht.peer_id, "screen-outlier")
        assert ga.publish_once() == 0

    def test_failed_store_requeues_receipt(self):
        """A transient store failure (outage, chaos blackout rule on
        'store') must retry next period, not silently lose a one-shot
        offense's receipt — the exact hazard the gossip graftlint
        fixture pins."""
        (ga, la), (gb, lb) = self._pair()
        offender = "ee" * 32
        la.strike(offender, "corrupt-chunk")
        real_store = ga.dht.store
        ga.dht.store = lambda *a, **k: False        # outage
        assert ga.publish_once() == 0
        ga.dht.store = real_store                   # heals
        assert ga.publish_once() == 1               # requeued, retried
        assert gb.fold_once() == 1
        assert lb.remote_score(offender) > 0

        # a store that RAISES mid-batch requeues the remainder too
        la.strike("aa" * 32, "corrupt-chunk")
        la.strike("bb" * 32, "corrupt-chunk")

        def boom(*a, **k):
            raise OSError("dht down")
        ga.dht.store = boom
        assert ga.publish_once() == 0
        ga.dht.store = real_store
        assert ga.publish_once() == 2

    def test_garbage_in_store_ignored(self):
        (ga, la), (gb, lb) = self._pair()
        ga.dht.store("g_strikes", "junk", b"not a receipt", 10 ** 10)
        ga.dht.store("g_strikes", "junk2", {"not": "bytes"}, 10 ** 10)
        assert gb.fold_once() == 0

    def test_worker_thread_stops_clean(self):
        (ga, la), _ = self._pair()
        ga.period = 0.05
        ga.start()
        la.strike("ee" * 32, "screen-outlier")
        deadline = time.monotonic() + 5
        while ga.published == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        ga.stop()
        assert not ga.is_alive()
        assert ga.published >= 1


# -- byzantine plan parsing / tamper seam ----------------------------------

class TestByzantinePlan:
    def test_roundtrip_and_strict_parse(self):
        plan = FaultPlan(seed=3, byzantine=(
            ByzantineOp(kind="scale", factor=-10.0, start_epoch=1,
                        end_epoch=5),))
        assert plan.enabled
        assert FaultPlan.from_json(plan.to_json()) == plan
        with pytest.raises(ValueError, match="unknown byzantine kind"):
            FaultPlan.from_dict({"byzantine": [{"kind": "signflip"}]})
        with pytest.raises(ValueError, match="unknown byzantine op key"):
            FaultPlan.from_dict(
                {"byzantine": [{"kind": "scale", "factr": 2.0}]})
        with pytest.raises(ValueError, match="needs a 'kind'"):
            FaultPlan.from_dict({"byzantine": [{"factor": 2.0}]})
        with pytest.raises(ValueError, match="weight_inflate"):
            ByzantineOp(kind="weight_inflate", factor=-1.0)
        with pytest.raises(ValueError, match="finite"):
            ByzantineOp(kind="scale", factor=float("inf"))
        with pytest.raises(ValueError, match="finite"):
            ByzantineOp(kind="scale", factor=float("nan"))
        with pytest.raises(ValueError, match="window"):
            ByzantineOp(kind="sign_flip", start_epoch=5, end_epoch=2)

    def test_tamper_kinds_and_epoch_window(self):
        stub = _GossipStub({})
        chaos = ChaosDHT(stub, FaultPlan(seed=1, byzantine=(
            ByzantineOp(kind="sign_flip", start_epoch=2, end_epoch=4),)))
        t = [np.arange(4, dtype=np.float32)]
        out, w = chaos.tamper_contribution(1, t, 3.0)
        assert out is t and w == 3.0          # outside the window: untouched
        out, w = chaos.tamper_contribution(2, t, 3.0)
        np.testing.assert_array_equal(out[0], -t[0])
        assert w == 3.0
        out, w = chaos.tamper_contribution(4, t, 3.0)
        assert out is t                        # window closed

        chaos2 = ChaosDHT(stub, FaultPlan(byzantine=(
            ByzantineOp(kind="weight_inflate", factor=1e9),)))
        out, w = chaos2.tamper_contribution(0, t, 3.0)
        assert out is t and w == 1e9           # data honest, claim inflated

        chaos3 = ChaosDHT(stub, FaultPlan(byzantine=(
            ByzantineOp(kind="scale", factor=-10.0),)))
        out, _ = chaos3.tamper_contribution(0, t, 3.0)
        np.testing.assert_array_equal(out[0], -10.0 * t[0])

    def test_garbage_is_seed_deterministic(self):
        stub = _GossipStub({})
        plan = FaultPlan(seed=9, byzantine=(
            ByzantineOp(kind="garbage", factor=100.0),))
        t = [np.zeros(64, np.float32)]
        a, _ = ChaosDHT(stub, plan).tamper_contribution(3, t, 1.0)
        b, _ = ChaosDHT(stub, plan).tamper_contribution(3, t, 1.0)
        c, _ = ChaosDHT(stub, plan).tamper_contribution(4, t, 1.0)
        np.testing.assert_array_equal(a[0], b[0])
        assert not np.array_equal(a[0], c[0])  # epoch-varying
        assert np.linalg.norm(a[0]) > 100.0    # actually loud

    def test_inert_wrapper_tamper_is_identity(self):
        stub = _GossipStub({})
        chaos = ChaosDHT(stub, FaultPlan(seed=1))
        t = [np.arange(4, dtype=np.float32)]
        out, w = chaos.tamper_contribution(0, t, 2.0)
        assert out is t and w == 2.0
        assert chaos.injected == {}


# -- real-socket integration ----------------------------------------------

def _det_swarm(n, base=61):
    from dalle_tpu.swarm.identity import Ed25519PrivateKey
    nodes = []
    for i in range(n):
        peers = [nodes[0].visible_address] if nodes else []
        ident = Identity(Ed25519PrivateKey.from_private_bytes(
            bytes([base + i]) * 32))
        nodes.append(DHT(initial_peers=peers, identity=ident,
                         rpc_timeout=2.0))
    return nodes


def _run_threads(fns, timeout=60):
    results = [None] * len(fns)
    errors = []

    def wrap(i, fn):
        try:
            results[i] = fn()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i, fn))
               for i, fn in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    if errors:
        raise errors[0]
    return results


def _round(dhts, prefix, tensors, *, screen=None, max_peer_weight=None,
           reports=None, ledgers=None, min_group=None, at=8.0):
    n = len(dhts)
    min_group = n if min_group is None else min_group

    def peer(i):
        g = make_group(dhts[i], prefix, epoch=0, weight=1.0,
                       matchmaking_time=3.0, min_group_size=min_group)
        assert g is not None and g.size == n
        return g, run_allreduce(
            dhts[i], g, prefix, 0, tensors[i], weight=1.0,
            allreduce_timeout=at, sender_timeout=1.5,
            codec=compression.NONE,
            report=None if reports is None else reports[i],
            ledger=None if ledgers is None else ledgers[i],
            screen=screen, max_peer_weight=max_peer_weight)

    return _run_threads([lambda i=i: peer(i) for i in range(n)])


class TestScreeningIntegration:
    def test_sign_flip_screened_at_every_honest_owner(self):
        """Tentpole pin: 5 peers, one contributing validly-signed
        sign-flipped data through the byzantine seam. Every honest part
        owner must drop it (attributable screen-outlier strike) and
        average the honest contributions EXACTLY — drop/keep, never
        reweight."""
        nodes = _det_swarm(5)
        pids = [n.peer_id for n in nodes]
        bad_i = 2
        dhts = list(nodes)
        dhts[bad_i] = ChaosDHT(nodes[bad_i], FaultPlan(
            seed=1, byzantine=(ByzantineOp(kind="sign_flip"),)))
        rng = np.random.RandomState(5)
        base = rng.randint(-8, 9, size=400).astype(np.float32)
        tensors = [[base + i] for i in range(5)]  # integer, non-IID
        reports = [dict() for _ in range(5)]
        ledgers = [PeerHealthLedger() for _ in range(5)]
        try:
            results = _round(dhts, "sf", tensors,
                             screen=GradientScreen(),
                             reports=reports, ledgers=ledgers)
        finally:
            for n in nodes:
                n.shutdown()
        honest = [i for i in range(5) if i != bad_i]
        group = results[honest[0]][0]
        member_ids = [m.peer_id for m in group.members]
        flats = [flatten_tensors(t) for t in tensors]
        slices = _part_slices(flats[0].size, 5)
        honest_avg = sum(flats[i] for i in honest) / len(honest)
        for i in honest:
            assert pids[bad_i] in reports[i]["screened_senders"]
            assert not reports[i]["complete"]
            assert ledgers[i].score(pids[bad_i]) == pytest.approx(2.0)
            my_part = member_ids.index(pids[i])
            lo, hi = slices[my_part]
            got = flatten_tensors(results[i][1])
            np.testing.assert_array_equal(got[lo:hi], honest_avg[lo:hi])

    def test_weight_overclaim_dropped_and_struck(self):
        """Satellite pin: a signed frame claiming weight=1e9 (honest
        DATA — no value screen can see it) is dropped wholesale with an
        attributable weight-overclaim strike; honest parts average over
        honest claims only."""
        nodes = _det_swarm(3, base=81)
        pids = [n.peer_id for n in nodes]
        bad_i = 1
        dhts = list(nodes)
        dhts[bad_i] = ChaosDHT(nodes[bad_i], FaultPlan(
            seed=2, byzantine=(
                ByzantineOp(kind="weight_inflate", factor=1e9),)))
        rng = np.random.RandomState(3)
        base = rng.randint(-8, 9, size=300).astype(np.float32)
        tensors = [[base + 2 * i] for i in range(3)]
        reports = [dict() for _ in range(3)]
        ledgers = [PeerHealthLedger() for _ in range(3)]
        try:
            results = _round(dhts, "wo", tensors, max_peer_weight=100.0,
                             reports=reports, ledgers=ledgers)
        finally:
            for n in nodes:
                n.shutdown()
        honest = [i for i in range(3) if i != bad_i]
        group = results[honest[0]][0]
        member_ids = [m.peer_id for m in group.members]
        flats = [flatten_tensors(t) for t in tensors]
        slices = _part_slices(flats[0].size, 3)
        honest_avg = sum(flats[i] for i in honest) / len(honest)
        for i in honest:
            assert reports[i]["overweight_senders"] == [pids[bad_i]]
            assert ledgers[i].score(pids[bad_i]) == pytest.approx(2.0)
            my_part = member_ids.index(pids[i])
            lo, hi = slices[my_part]
            got = flatten_tensors(results[i][1])
            np.testing.assert_array_equal(got[lo:hi], honest_avg[lo:hi])

    def test_disabled_matches_enabled_honest_byte_identical(self):
        """The transparency pin (chaos-layer standard): the full
        matchmaking + allreduce stack with deterministic identities and
        INTEGER tensors, run once with screening+clamp off (the
        pre-change path, bit-exact by construction — screen=None takes
        the untouched streaming branch) and once with the whole defense
        enabled on an honest roster — byte-identical averages."""
        rng = np.random.RandomState(17)
        tensors = [[rng.randint(-8, 9, size=512).astype(np.float32)]
                   for _ in range(4)]

        def round_once(defended):
            nodes = _det_swarm(4, base=91)
            try:
                return _round(
                    nodes, "tp", tensors,
                    screen=GradientScreen() if defended else None,
                    max_peer_weight=100.0 if defended else None)
            finally:
                for n in nodes:
                    n.shutdown()

        plain = round_once(defended=False)
        defended = round_once(defended=True)
        for p, d in zip(plain, defended):
            np.testing.assert_array_equal(p[1][0], d[1][0])

    def test_under_delivered_round_withholds_parts(self):
        """A 5-member roster clears the screen quorum, but only 3
        members actually participate (churn / a roster split while
        offenders are penalized at different peers). The screen cannot
        certify a 3-delivery set it promised to screen — averaging it
        unscreened is the window a colluding minority needs (the
        byzantine soak caught a transition epoch exploiting exactly
        this) — so every part is WITHHELD: each participant's result
        is bit-identical to its own local tensors."""
        nodes = _det_swarm(5, base=31)
        live = [0, 1, 2]  # members 3 and 4 announce, then go silent
        rng = np.random.RandomState(9)
        tensors = [[rng.randint(-8, 9, size=200).astype(np.float32)]
                   for _ in range(5)]
        reports = [dict() for _ in range(5)]

        def peer(i):
            g = make_group(nodes[i], "ud", epoch=0, weight=1.0,
                           matchmaking_time=3.0, min_group_size=5)
            assert g is not None and g.size == 5
            if i not in live:
                return g, None  # announced, never participates
            return g, run_allreduce(
                nodes[i], g, "ud", 0, tensors[i], weight=1.0,
                allreduce_timeout=8.0, sender_timeout=1.5,
                codec=compression.NONE, report=reports[i],
                screen=GradientScreen())

        try:
            results = _run_threads([lambda i=i: peer(i)
                                    for i in range(5)])
        finally:
            for n in nodes:
                n.shutdown()
        for i in live:
            assert not reports[i]["complete"]
            assert reports[i]["screened_senders"] == []  # no verdicts
            # every part kept local values: nothing unscreened landed
            np.testing.assert_array_equal(results[i][1][0],
                                          tensors[i][0])

    def test_two_peer_unattributability_preserved(self):
        """A 2-peer swarm must never screen: either peer calling the
        other an outlier is a veto (the same rule that keeps 2-peer
        timeout bans strike-less). The attacker's data lands — the
        documented small-swarm gap — but NO strikes are recorded."""
        nodes = _det_swarm(2, base=71)
        dhts = list(nodes)
        dhts[1] = ChaosDHT(nodes[1], FaultPlan(
            seed=3, byzantine=(ByzantineOp(kind="sign_flip"),)))
        tensors = [[np.full(64, 4.0, np.float32)] for _ in range(2)]
        reports = [dict() for _ in range(2)]
        ledgers = [PeerHealthLedger() for _ in range(2)]
        try:
            results = _round(dhts, "2p", tensors,
                             screen=GradientScreen(),
                             reports=reports, ledgers=ledgers)
        finally:
            for n in nodes:
                n.shutdown()
        assert reports[0]["screened_senders"] == []
        assert ledgers[0].snapshot() == {}
        # the flip DID land: (4 + -4) / 2 = 0 — screening is honest
        # about what it cannot decide at this size
        np.testing.assert_array_equal(results[0][1][0],
                                      np.zeros(64, np.float32))


class TestAbsNormCeiling:
    """The absolute per-sender norm ceiling: active at ANY sender
    count (narrowing the <4-sender gap where LOO screening must
    skip), struck only at quorum."""

    def test_validation_and_disabled_default(self):
        with pytest.raises(ValueError):
            ScreenPolicy(abs_norm_ceiling=-1.0)
        assert ScreenPolicy().abs_norm_ceiling == 0.0
        s = GradientScreen(ScreenPolicy())
        assert not s.over_ceiling(np.full(64, 1e9, np.float32))

    def test_quorum_roster_ceiling_drop_is_struck(self):
        s = GradientScreen(ScreenPolicy(abs_norm_ceiling=100.0))
        rng = np.random.RandomState(0)
        contribs = {k: (1.0, rng.randn(64).astype(np.float32))
                    for k in range(4)}
        contribs[2] = (1.0, np.full(64, 50.0, np.float32))  # norm 400
        v = s.screen(contribs)
        assert not v.skipped
        assert list(v.dropped) == [2]
        assert v.dropped[2].startswith("abs-norm")
        assert v.dropped_unstruck == {}

    def test_below_quorum_drop_is_unstruck(self):
        s = GradientScreen(ScreenPolicy(abs_norm_ceiling=100.0))
        rng = np.random.RandomState(1)
        contribs = {0: (1.0, rng.randn(64).astype(np.float32)),
                    1: (1.0, np.full(64, 50.0, np.float32))}
        v = s.screen(contribs)
        assert v.skipped
        assert v.dropped == {}
        assert list(v.dropped_unstruck) == [1]

    def test_two_peer_round_drops_without_strike(self):
        """Integration: a 2-peer socket round where one sender's
        segment is over the ceiling — the contribution is dropped
        (clamp IS the defense) but nobody is struck (2-peer
        unattributability preserved)."""
        nodes = _det_swarm(2, base=87)
        pids = [n.peer_id for n in nodes]
        base = np.arange(300, dtype=np.float32) % 7 - 3
        tensors = [[base.copy()], [np.full(300, 1000.0, np.float32)]]
        reports = [dict() for _ in range(2)]
        ledgers = [PeerHealthLedger() for _ in range(2)]
        screen = GradientScreen(ScreenPolicy(abs_norm_ceiling=500.0))
        try:
            results = _round(nodes, "ce", tensors, screen=screen,
                             reports=reports, ledgers=ledgers)
        finally:
            for n in nodes:
                n.shutdown()
        member_ids = [m.peer_id for m in results[0][0].members]
        flats = [flatten_tensors(t) for t in tensors]
        slices = _part_slices(flats[0].size, 2)
        # peer 0's part averages over peer 0 alone (peer 1 dropped);
        # no strike anywhere
        assert pids[1] in reports[0]["screened_senders"]
        assert not reports[0]["complete"]
        assert ledgers[0].snapshot() == {} and ledgers[1].snapshot() == {}
        p0_part = member_ids.index(pids[0])
        lo, hi = slices[p0_part]
        got = flatten_tensors(results[0][1])
        np.testing.assert_array_equal(got[lo:hi], flats[0][lo:hi])


class TestProgressLeadBound:
    """The plausible-lead bound on progress-record epoch claims: the
    clamp is the defense (always), the strike fires only beyond 100x
    the bound — honest overshoot under slow local rounds is clamped,
    never struck."""

    def _converged(self, tracker, want_peers=1, timeout=10):
        deadline = time.monotonic() + timeout
        gp = tracker.global_progress(force_refresh=True)
        while gp.reporting_peers < want_peers \
                and time.monotonic() < deadline:
            time.sleep(0.1)
            gp = tracker.global_progress(force_refresh=True)
        assert gp.reporting_peers >= want_peers
        return gp

    def test_absurd_epoch_claim_clamped_and_struck_once(self):
        from dalle_tpu.swarm.progress import ProgressTracker
        nodes = _det_swarm(3, base=93)
        led = PeerHealthLedger()
        try:
            tracker = ProgressTracker(nodes[0], "pl", target_batch_size=64,
                                      ledger=led, min_refresh_period=0.0,
                                      max_epoch_lead=2)
            # an in-bound honest reporter: the strike's corroboration
            # cohort (an outlying clock vs an in-bound peer is a
            # fabrication; all-peers-ahead would mean OUR clock is
            # stale — see test below)
            honest = ProgressTracker(nodes[1], "pl", target_batch_size=64)
            honest.report_local_progress(0, 5, force=True)
            liar = ProgressTracker(nodes[2], "pl", target_batch_size=64)
            liar.report_local_progress(10 ** 6, 40, force=True)
            time.sleep(0.4)
            gp = self._converged(tracker, want_peers=2)
            # the aggregate epoch (and with it the resync target) is
            # bounded to local + max_epoch_lead, and the clamped
            # record's samples never merge into a bucket this node
            # can't place
            assert gp.epoch <= 2
            assert gp.samples_accumulated <= 5
            assert led.score(nodes[2].peer_id) == pytest.approx(1.0)
            # dedup per (peer, claimed epoch): polling is not a flood
            tracker.global_progress(force_refresh=True)
            assert led.score(nodes[2].peer_id) == pytest.approx(1.0)
            assert led.score(nodes[1].peer_id) == 0.0
        finally:
            for n in nodes:
                n.shutdown()

    def test_stale_local_clock_never_strikes_the_swarm(self):
        """A restarted/partitioned node whose whole cohort is far
        ahead must conclude its OWN clock is stale — clamp (the
        resync trigger still fires), but never strike, and never
        gossip receipts against an honest swarm."""
        from dalle_tpu.swarm.progress import ProgressTracker
        nodes = _det_swarm(3, base=89)
        led = PeerHealthLedger()
        try:
            tracker = ProgressTracker(nodes[0], "sc", target_batch_size=64,
                                      ledger=led, min_refresh_period=0.0,
                                      max_epoch_lead=2)
            for i in (1, 2):  # the swarm is honestly at epoch 500
                ProgressTracker(nodes[i], "sc", target_batch_size=64) \
                    .report_local_progress(500, 5, force=True)
            time.sleep(0.4)
            gp = self._converged(tracker, want_peers=2)
            assert gp.epoch == 2          # clamped: resync still fires
            assert led.snapshot() == {}   # nobody struck
        finally:
            for n in nodes:
                n.shutdown()

    def test_slow_round_honest_overshoot_clamped_never_struck(self):
        """The pinned satellite case: a peer legitimately several
        epochs ahead of a slow/partitioned local node is clamped in
        the aggregate but NEVER struck — only orders-of-magnitude
        fabrications are unambiguous."""
        from dalle_tpu.swarm.progress import ProgressTracker
        nodes = _det_swarm(2, base=97)
        led = PeerHealthLedger()
        try:
            tracker = ProgressTracker(nodes[0], "os", target_batch_size=64,
                                      ledger=led, min_refresh_period=0.0,
                                      max_epoch_lead=2)
            ahead = ProgressTracker(nodes[1], "os", target_batch_size=64)
            ahead.report_local_progress(7, 10, force=True)  # lead 7 > 2
            time.sleep(0.4)
            gp = self._converged(tracker)
            assert gp.epoch == 2          # clamped to local + lead
            assert led.snapshot() == {}   # ...but an honest peer
            # the clamp window slides as the local node catches up
            tracker.local_epoch = 6
            gp = tracker.global_progress(force_refresh=True)
            assert gp.epoch == 7          # now inside the bound
        finally:
            for n in nodes:
                n.shutdown()

    def test_disabled_bound_keeps_raw_epochs(self):
        from dalle_tpu.swarm.progress import ProgressTracker
        nodes = _det_swarm(2, base=99)
        try:
            tracker = ProgressTracker(nodes[0], "nl", target_batch_size=64,
                                      min_refresh_period=0.0,
                                      max_epoch_lead=0)
            peer = ProgressTracker(nodes[1], "nl", target_batch_size=64)
            peer.report_local_progress(50, 1, force=True)
            time.sleep(0.4)
            gp = self._converged(tracker)
            assert gp.epoch == 50
        finally:
            for n in nodes:
                n.shutdown()


class TestProgressOverclaim:
    def test_absurd_claim_clamped_and_struck_once(self):
        from dalle_tpu.swarm.progress import ProgressTracker
        nodes = _det_swarm(2, base=51)
        led = PeerHealthLedger()
        try:
            tracker = ProgressTracker(nodes[0], "po", target_batch_size=64,
                                      ledger=led,
                                      min_refresh_period=0.0)
            liar = ProgressTracker(nodes[1], "po", target_batch_size=64)
            liar.report_local_progress(0, 10 ** 9, force=True)
            time.sleep(0.4)  # let the record replicate
            deadline = time.monotonic() + 10
            gp = tracker.global_progress(force_refresh=True)
            while gp.reporting_peers < 1 and time.monotonic() < deadline:
                time.sleep(0.1)
                gp = tracker.global_progress(force_refresh=True)
            assert gp.reporting_peers == 1
            # per-peer share capped at the whole swarm target: the
            # epoch clock cannot be stolen by one absurd signed claim
            assert gp.samples_accumulated <= 64
            assert led.score(nodes[1].peer_id) == pytest.approx(1.0)
            # sub-second polling must not turn one bad record into a
            # strike flood: dedup per (peer, claimed epoch)
            tracker.global_progress(force_refresh=True)
            tracker.global_progress(force_refresh=True)
            assert led.score(nodes[1].peer_id) == pytest.approx(1.0)
            # a FULL dedup set (an epoch-churning flooder) stops
            # striking — clamping alone bounds the damage — instead of
            # re-enabling the per-poll strike flood
            tracker._overclaim_struck = {
                ("x", i) for i in range(4096)}
            liar.report_local_progress(1, 10 ** 9, force=True)
            time.sleep(0.4)
            before = led.score(nodes[1].peer_id)
            gp = tracker.global_progress(force_refresh=True)
            assert gp.samples_accumulated <= 64  # still clamped
            assert led.score(nodes[1].peer_id) == pytest.approx(before)
        finally:
            for n in nodes:
                n.shutdown()

    def test_honest_overshoot_not_struck(self):
        """Accumulating far past target while a slow round is in
        flight is NORMAL (samples grow for the round's whole
        wall-clock; 12x a small target observed in the 2-peer CPU
        drive): capped in the sum, but never a strike."""
        from dalle_tpu.swarm.progress import ProgressTracker
        nodes = _det_swarm(2, base=41)
        led = PeerHealthLedger()
        try:
            tracker = ProgressTracker(nodes[0], "ho", target_batch_size=64,
                                      ledger=led, min_refresh_period=0.0)
            honest = ProgressTracker(nodes[1], "ho", target_batch_size=64)
            honest.report_local_progress(0, 800, force=True)  # 12.5x cap
            time.sleep(0.4)
            deadline = time.monotonic() + 10
            gp = tracker.global_progress(force_refresh=True)
            while gp.reporting_peers < 1 and time.monotonic() < deadline:
                time.sleep(0.1)
                gp = tracker.global_progress(force_refresh=True)
            assert gp.samples_accumulated <= 64
            assert led.snapshot() == {}
        finally:
            for n in nodes:
                n.shutdown()


# -- the byzantine soak gate ----------------------------------------------

class TestByzantineSoak:
    def test_schedule_is_seed_deterministic(self):
        from scripts.churn_soak import build_byzantine_schedule
        a = build_byzantine_schedule(seed=4, n_peers=5, epochs=3)
        b = build_byzantine_schedule(seed=4, n_peers=5, epochs=3)
        c = build_byzantine_schedule(seed=5, n_peers=5, epochs=3)
        assert a == b and a != c
        kinds = sorted(x["kind"] for x in a["attacks"])
        assert kinds == ["scale", "sign_flip"]
        assert len({x["peer"] for x in a["attacks"]}) == 2

    def test_fast_soak(self, tmp_path):
        """Tier-1 byzantine gate: 5 peers, 1 sign-flip + 1 scale
        attacker, control pass + attack pass over one schedule. The
        script's own oracles assert zero control strikes, bit-exact
        honest convergence under attack, and every attacker struck in
        every honest ledger within <= 2 epochs with gossiped receipt
        corroboration."""
        from scripts.churn_soak import main
        out = tmp_path / "BYZANTINE_SOAK.json"
        rc = main(["--byzantine", "--peers", "5", "--epochs", "3",
                   "--seed", "7", "--matchmaking-time", "1.2",
                   "--allreduce-timeout", "5", "--deadline", "150",
                   "--out", str(out)])
        assert rc == 0, f"byzantine soak reported a violation (see {out})"
        import json
        report = json.loads(out.read_text())
        assert report["pass"] is True and report["violations"] == []
        assert all(not r["first_strike"] for r in report["control"])
        honest = [r for r in report["attack"] if not r["attacker"]]
        assert len(honest) == 3
        assert len({r["fingerprint"] for r in honest}) == 1

    @pytest.mark.slow
    def test_full_soak(self, tmp_path):
        """The full-size byzantine soak (defaults-sized windows) —
        slow-marked; `scripts/churn_soak.py --byzantine` is the same
        gate from the command line."""
        from scripts.churn_soak import main
        out = tmp_path / "BYZANTINE_SOAK.json"
        rc = main(["--byzantine", "--peers", "5", "--epochs", "6",
                   "--seed", "11", "--deadline", "420",
                   "--out", str(out)])
        assert rc == 0
