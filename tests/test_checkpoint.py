"""Checkpoint/backup/NaN-rollback/resume tests (reference callback.py
semantics)."""

import logging

import jax
import jax.numpy as jnp
import numpy as np

from dalle_tpu.config import (CollabConfig, OptimizerConfig, PeerConfig,
                              TrainerConfig, tiny_model_config)
from dalle_tpu.models.dalle import DALLE, init_params
from dalle_tpu.optim import make_optimizer
from dalle_tpu.training.checkpoint import (CheckpointManager,
                                           params_are_finite)
from dalle_tpu.training.steps import TrainState


def _state(seed=0, lr=1e-3):
    cfg = tiny_model_config()
    model = DALLE(cfg)
    params = init_params(model, jax.random.PRNGKey(seed))
    # small min_8bit_size so the checkpoint covers quantized moments
    tx = make_optimizer(OptimizerConfig(
        learning_rate=lr, warmup_steps=2, total_steps=100,
        min_8bit_size=2048, block_size=256))
    return cfg, model, tx, TrainState.create(params, tx)


def _assert_states_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestCheckpointManager:
    def test_roundtrip_including_quantized_moments(self, tmp_path):
        cfg, model, tx, state = _state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(state, epoch=5)
        template = _state(seed=1)[3]  # different values, same structure
        restored, epoch = mgr.restore_latest(template)
        assert epoch == 5
        _assert_states_equal(restored, state)

    def test_keep_prunes_old(self, tmp_path):
        _, _, _, state = _state()
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for e in (1, 2, 3, 4):
            mgr.save(state, epoch=e)
            mgr.flush()  # back-to-back async saves coalesce by design
        assert [e for e, _ in mgr.checkpoints()] == [3, 4]

    def test_backup_preferred_when_fresher(self, tmp_path):
        _, _, _, state = _state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(state, epoch=3)
        newer = state.replace(step=state.step + 7)
        mgr.save_backup(newer, epoch=9)
        restored, epoch = mgr.restore_latest(state)
        assert epoch == 9
        assert int(restored.step) == int(state.step) + 7

    def test_corrupt_file_skipped(self, tmp_path):
        _, _, _, state = _state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(state, epoch=1)
        (tmp_path / "ckpt_00000009.msgpack").write_bytes(b"garbage")
        restored = mgr.restore_latest(state)
        assert restored is not None and restored[1] == 1

    def test_params_are_finite(self):
        _, _, _, state = _state()
        assert params_are_finite(state.params)
        bad = jax.tree.map(lambda x: x.at[..., 0].set(jnp.nan)
                           if x.ndim else x, state.params)
        assert not params_are_finite(bad)


def _make_task(tmp_path, seed=0):
    from dalle_tpu.task import TrainingTask

    model = tiny_model_config()
    opt = OptimizerConfig(learning_rate=3e-3, warmup_steps=2,
                          total_steps=100)
    trainer = TrainerConfig(per_device_batch=2, seed=seed)  # dp=-1: 8 devs
    collab = CollabConfig(run_id=f"ck-{tmp_path.name}",
                          target_batch_size=16, matchmaking_time=0.5,
                          allreduce_timeout=5.0, averaging_timeout=10.0,
                          average_state_every=0)
    peer = PeerConfig(identity_path=str(tmp_path / "id.pem"))
    return TrainingTask(model, opt, trainer, collab, peer)


class TestLoopRecovery:
    def test_kill_and_resume(self, tmp_path):
        """Train, stop, start a fresh task: it resumes from the checkpoint
        (same epoch, same params) and keeps training."""
        from dalle_tpu.training.loop import train_loop

        ckdir = str(tmp_path / "ck")
        task = _make_task(tmp_path / "a")
        try:
            reports = train_loop(task, max_epochs=3, warmup_steps=0,
                                 checkpoint_dir=ckdir, save_every=1,
                                 backup_every=1)
            assert reports[-1].epoch == 3
            params_before = jax.device_get(
                task.collab_optimizer.state.params)
        finally:
            task.shutdown()

        task2 = _make_task(tmp_path / "b")
        try:
            collab2 = task2.collab_optimizer
            assert collab2.local_epoch == 0
            reports2 = train_loop(task2, max_epochs=5, warmup_steps=0,
                                  checkpoint_dir=ckdir, save_every=1,
                                  backup_every=1)
            # resumed at 3 (not retrained from scratch), continued to 5
            assert collab2.local_epoch == 5
            assert all(r.epoch > 3 for r in reports2)
        finally:
            task2.shutdown()
        del params_before

    def test_nan_step_rolls_back_to_backup(self, tmp_path):
        """An optimizer step that produces NaN params is detected by the
        finite sweep and rolled back to the backup, after which training
        recovers (reference callback.py:50-54,95-100)."""
        from dalle_tpu.training.loop import train_loop

        ckdir = str(tmp_path / "ck")
        task = _make_task(tmp_path / "a")
        try:
            collab = task.collab_optimizer
            train_loop(task, max_epochs=2, warmup_steps=0,
                       checkpoint_dir=ckdir, save_every=1, backup_every=1)
            assert collab.local_epoch == 2

            orig_apply = collab.apply_step
            poisoned_calls = {"n": 0}

            def poisoned(state, grads):
                state = orig_apply(state, grads)
                poisoned_calls["n"] += 1
                if poisoned_calls["n"] == 1:  # first step after resume
                    state = state.replace(params=jax.tree.map(
                        lambda x: x * jnp.nan, state.params))
                return state

            collab.apply_step = poisoned
            train_loop(task, max_epochs=3, warmup_steps=0,
                       checkpoint_dir=ckdir, save_every=1, backup_every=1)
            assert poisoned_calls["n"] >= 2  # rollback forced a redo
            assert params_are_finite(collab.state.params)
            assert collab.local_epoch >= 3
        finally:
            task.shutdown()

class TestAsyncWrites:
    """The async writer (VERDICT r4 weak #3): saves return immediately,
    restores see queued writes, coalescing keeps latest, and a state
    mutated after save is NOT what lands on disk (the snapshot is the
    immutable tree captured at enqueue time)."""

    def test_save_returns_before_bytes_land_then_flush(self, tmp_path):
        import os
        _, _, _, state = _state()
        mgr = CheckpointManager(str(tmp_path))
        path = mgr.save(state, epoch=1)
        mgr.flush()
        assert os.path.exists(path)
        assert mgr.last_write_error is None

    def test_restore_flushes_queued_write(self, tmp_path):
        """restore_latest right after save must see the queued write —
        the NaN-rollback path depends on this ordering."""
        _, _, _, state = _state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save_backup(state, epoch=4)
        restored = mgr.restore_backup(state)  # no explicit flush
        assert restored is not None and restored[1] == 4

    def test_backup_coalescing_keeps_latest(self, tmp_path):
        _, _, _, state = _state()
        mgr = CheckpointManager(str(tmp_path))
        for e in range(1, 6):
            mgr.save_backup(state.replace(step=state.step + e), epoch=e)
        mgr.flush()
        restored = mgr.restore_backup(state)
        assert restored is not None
        # the LATEST queued backup won (intermediates are droppable)
        assert restored[1] == 5

    def test_snapshot_is_capture_time_state(self, tmp_path):
        """Mutating the live state after save must not change what the
        writer serializes: jax trees are immutable, the captured reference
        is the snapshot."""
        import jax.numpy as jnp
        mgr = CheckpointManager(str(tmp_path))
        live = {"w": jnp.ones((8,))}
        mgr.save(live, epoch=1)
        # the optimizer apply REBINDS the state to a new tree (TrainState
        # .replace / apply_step both build fresh objects); the enqueued
        # reference keeps pointing at the old, untouched tree
        live = {"w": live["w"] * 100.0}
        del live
        mgr.flush()
        restored = mgr.restore_latest({"w": jnp.zeros((8,))})
        assert restored is not None
        np.testing.assert_array_equal(np.asarray(restored[0]["w"]),
                                      np.ones(8, np.float32))

    def test_write_error_is_surfaced_not_fatal(self, tmp_path):
        _, _, _, state = _state()
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.flush()
        # point the directory at an unwritable location
        mgr.directory = str(tmp_path / "missing" / "\0bad")
        mgr.save_backup(state, epoch=1)
        mgr.flush()  # returns; does not raise
        assert mgr.last_write_error is not None

    def test_close_bounded_on_wedged_write(self, tmp_path, caplog):
        """A wedged filesystem write must not block shutdown forever
        (ADVICE r5): close() bounds its flush and abandons the backlog
        with a warning."""
        import threading
        import time
        mgr = CheckpointManager(str(tmp_path))
        release = threading.Event()

        def wedged():
            release.wait(30)

        mgr._writer.submit("backup", wedged, "wedged@1")
        t0 = time.monotonic()
        with caplog.at_level(logging.WARNING,
                             logger="dalle_tpu.training.checkpoint"):
            mgr.close(flush_timeout=0.3)
        assert time.monotonic() - t0 < 5.0
        assert any("did not drain" in r.message for r in caplog.records)
        release.set()

    def test_close_default_drains_cleanly(self, tmp_path):
        _, _, _, state = _state()
        mgr = CheckpointManager(str(tmp_path))
        path = mgr.save(state, epoch=2)
        mgr.close()  # default timeout: drains the queued write first
        import os
        assert os.path.exists(path)


class TestLargeCheckpoint:
    def test_restore_past_msgpack_default_buffer(self, tmp_path):
        """Flagship-scale blobs exceed msgpack.Unpacker's default
        100 MB max_buffer_size; restore must not BufferFull (found by
        the r4 sustained run's resume — tiny-model tests never hit
        it)."""
        from dalle_tpu.training.checkpoint import CheckpointManager

        big = {"w": jnp.arange(30_000_000, dtype=jnp.float32)}  # ~120 MB
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(big, epoch=7)
        restored = mgr.restore_latest(
            {"w": jnp.zeros(30_000_000, jnp.float32)})
        assert restored is not None
        state, epoch = restored
        assert epoch == 7
        np.testing.assert_array_equal(np.asarray(state["w"][-4:]),
                                      np.arange(30_000_000,
                                                dtype=np.float32)[-4:])
