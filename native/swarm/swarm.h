/* C API of the dalle_tpu swarm peer daemon.
 *
 * TPU-native equivalent of the reference's p2p substrate: the reference
 * (learning-at-home/dalle) drives a go-libp2p-daemon ("p2pd", Go) through
 * hivemind.DHT (task.py:104-114, arguments.py:93-124) for Kademlia routing,
 * TTL'd record storage with subkeys, and peer-to-peer tensor part streams.
 * This library provides the same substrate as an in-process C++ daemon:
 * every node runs a TCP listener plus a Kademlia-style routing table and
 * record store, and exposes a tagged message data plane for the butterfly
 * all-reduce. Signing/validation of records is the Python layer's job
 * (parity with hivemind, whose RecordValidators are Python classes around
 * the Go transport — reference utils.py:27-30).
 *
 * Thread-safety: all functions are safe to call from any thread. Multiple
 * nodes may live in one process (the localhost many-peer test strategy of
 * SURVEY.md section 4).
 */
#ifndef DALLE_TPU_SWARM_H_
#define DALLE_TPU_SWARM_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct SwarmNode SwarmNode;

/* Create a node listening on host:port (port 0 = ephemeral). id must point
 * at 32 bytes (sha256 of the peer's public key; the Python layer owns keys).
 * client_mode != 0 => no listener: outbound-only peer (reference
 * arguments.py:89-92). Returns NULL on failure. */
SwarmNode *swarm_node_create(const char *host, int port,
                             const uint8_t id[32], int client_mode);

/* Bound listen port (network byte order resolved), or 0 in client mode. */
int swarm_node_port(const SwarmNode *node);

/* Ping a bootstrap address and run an iterative self-lookup to populate the
 * routing table (reference initial_peers, arguments.py:100-106).
 * Returns 0 on success. */
int swarm_node_bootstrap(SwarmNode *node, const char *host, int port);

/* Store key/subkey=value with absolute unix expiration time onto the k
 * closest nodes (and locally). Returns number of remote replicas written
 * (>=0), or -1 on total failure. */
int swarm_node_store(SwarmNode *node, const uint8_t key[32],
                     const uint8_t *subkey, size_t subkey_len,
                     const uint8_t *value, size_t value_len,
                     double expiration);

/* Iterative FIND_VALUE. On success returns a malloc'd buffer (caller frees
 * with swarm_free) holding the merged subkey map:
 *   u32 count, then per entry: u32 subkey_len, subkey, u32 value_len,
 *   value, f64 expiration (bits, big-endian).
 * Expired entries are dropped; duplicate subkeys keep the latest
 * expiration. Returns NULL if nothing found. */
uint8_t *swarm_node_get(SwarmNode *node, const uint8_t key[32],
                        size_t *out_len);

/* Data plane: send a tagged message to a peer's listener. Blocks until
 * acked or the timeout elapses (timeout_ms <= 0 uses the node-wide RPC
 * timeout). Returns 0 on success. */
int swarm_node_send(SwarmNode *node, const char *host, int port,
                    uint64_t tag, const uint8_t *payload, size_t len,
                    int timeout_ms);

/* Pop the next message with this tag (FIFO per tag), waiting up to
 * timeout_ms. Returns malloc'd payload (swarm_free) or NULL on timeout. */
uint8_t *swarm_node_recv(SwarmNode *node, uint64_t tag, int timeout_ms,
                         size_t *out_len);

/* Mailbox: the pull-based half of the data plane, for client-mode peers
 * (outbound-only, no listener — reference arguments.py:89-92) that cannot
 * receive pushed messages. A listener posts a payload under a tag with an
 * absolute unix expiration; any peer may then FETCH it over a normal
 * outbound connection. One payload per tag (reposting replaces); expired
 * entries are garbage-collected. */
int swarm_node_post(SwarmNode *node, uint64_t tag, const uint8_t *payload,
                    size_t len, double expiration);

/* Fetch a mailbox entry from a remote peer. Single round trip; returns
 * malloc'd payload (swarm_free) or NULL if absent/expired/unreachable.
 * Callers poll. */
uint8_t *swarm_node_fetch(SwarmNode *node, const char *host, int port,
                          uint64_t tag, int timeout_ms, size_t *out_len);

/* Relay: a routable peer forwards traffic between client-mode peers that
 * cannot reach each other (the reference's libp2p relay/hole-punching
 * surface, arguments.py:89-124). A client-mode peer ATTACHES to a relay
 * over one persistent outbound connection; the relay then (a) forwards
 * tagged messages to it (swarm_node_relay_send from anyone) and (b)
 * forwards mailbox FETCHes to it and returns the replies — so an attached
 * peer can both receive pushes and serve its mailbox without a listener.
 */

/* Attach this node to a relay. Keeps one outbound connection open and
 * spawns a reader that enqueues forwarded messages into the normal recv
 * queues and answers forwarded fetches from the local mailbox. Re-attach
 * replaces the previous attachment. Returns 0 on success. */
int swarm_node_attach_relay(SwarmNode *node, const char *host, int port);

/* Send tag+payload to the peer with `target` id ATTACHED to the relay at
 * host:port. Returns 0 once the relay accepted and wrote the frame to the
 * attachment, -1 otherwise (target not attached / relay unreachable). */
int swarm_node_relay_send(SwarmNode *node, const char *host, int port,
                          const uint8_t target[32], uint64_t tag,
                          const uint8_t *payload, size_t len,
                          int timeout_ms);

/* Fetch a mailbox entry from an ATTACHED peer through its relay. Round
 * trip: caller -> relay -> attachment -> relay -> caller. Returns malloc'd
 * payload (swarm_free) or NULL. */
uint8_t *swarm_node_relay_fetch(SwarmNode *node, const char *host, int port,
                                const uint8_t target[32], uint64_t tag,
                                int timeout_ms, size_t *out_len);

/* Hole punch: DHT-coordinated TCP hole punching between two peers that
 * cannot accept inbound connections (the reference libp2p daemon's
 * transport-level hole punching; relay remains the fallback). Roles are
 * deterministic — the smaller node id dials, the larger accepts — so no
 * tie-break is needed when both directions would succeed.
 *
 *   port = swarm_node_punch_prepare(node, target_id);   // bind + advertise
 *   ...exchange (host, port) with the target through the DHT...
 *   swarm_node_punch_connect(node, target_id, host, port, timeout_ms);
 *
 * On success the connection becomes a DIRECT LINK: swarm_node_relay_send /
 * swarm_node_relay_fetch to that target use it instead of the relay, and
 * fall back to the relay automatically if the link dies. */

/* Bind the punch socket for `target`; returns the local port to
 * advertise, or -1. */
int swarm_node_punch_prepare(SwarmNode *node, const uint8_t target[32]);

/* Complete the punch against the target's advertised host:port (both
 * peers must call this concurrently). Verifies the peer's identity with
 * a hello exchange before registering the link. Returns 0 on success. */
int swarm_node_punch_connect(SwarmNode *node, const uint8_t target[32],
                             const char *host, int port, int timeout_ms);

/* 1 if a live punched link to `target` exists. */
int swarm_node_has_direct(SwarmNode *node, const uint8_t target[32]);

/* Host as observed by this node's relay (kAttachOk reports it): the
 * server-reflexive address a NAT'd peer advertises when coordinating a
 * punch. malloc'd (swarm_free) or NULL if never attached. */
uint8_t *swarm_node_observed_host(SwarmNode *node, size_t *out_len);

/* Number of relayed frames (sends + fetch rounds) this node has served
 * as a RELAY — lets tests observe punched links bypassing the relay. */
uint64_t swarm_node_relay_served(SwarmNode *node);

/* Routing table dump: malloc'd buffer of u32 count entries:
 * 32B id, u32 host_len, host, u16 port (BE). */
uint8_t *swarm_node_peers(SwarmNode *node, size_t *out_len);

/* Set RPC timeout (connect+roundtrip) in ms. Default 5000. */
void swarm_node_set_timeout(SwarmNode *node, int timeout_ms);

void swarm_node_destroy(SwarmNode *node);
void swarm_free(uint8_t *buf);

#ifdef __cplusplus
}
#endif

#endif /* DALLE_TPU_SWARM_H_ */
