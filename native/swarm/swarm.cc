/* dalle_tpu swarm peer daemon: Kademlia-style DHT + tagged message data
 * plane over TCP. See swarm.h for the capability contract and the mapping
 * onto the reference's go-libp2p-daemon substrate. */

#include "swarm.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <cerrno>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

using NodeId = std::array<uint8_t, 32>;

constexpr int kBucketSize = 16;   // Kademlia k
constexpr int kAlpha = 3;         // lookup parallelism (serialized batches)
constexpr uint8_t kPing = 1, kPong = 2, kStore = 3, kStoreOk = 4,
                  kFindNode = 5, kNodes = 6, kFindValue = 7, kValue = 8,
                  kMsg = 9, kMsgOk = 10, kFetch = 11, kFetchHit = 12,
                  kFetchMiss = 13,
                  /* relay plane */
                  kRelayAttach = 14, kAttachOk = 15, kRelaySend = 16,
                  kRelayMiss = 17, kRelayFetch = 18, kRelayReply = 19,
                  /* hole-punched direct links */
                  kPunchHello = 20;

/* How long a pooled / attachment connection may sit idle before its
 * blocking read gives up (the client pool simply reconnects). */
constexpr int kIdleMs = 60000;

double now_unix() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

/* ---------- byte buffer helpers (big-endian wire format) ---------- */

void put_u16(std::string &b, uint16_t v) {
  b.push_back(char(v >> 8));
  b.push_back(char(v & 0xff));
}
void put_u32(std::string &b, uint32_t v) {
  for (int i = 3; i >= 0; --i) b.push_back(char((v >> (8 * i)) & 0xff));
}
void put_u64(std::string &b, uint64_t v) {
  for (int i = 7; i >= 0; --i) b.push_back(char((v >> (8 * i)) & 0xff));
}
void put_f64(std::string &b, double v) {
  uint64_t bits;
  memcpy(&bits, &v, 8);
  put_u64(b, bits);
}
void put_bytes(std::string &b, const uint8_t *p, size_t n) {
  put_u32(b, uint32_t(n));
  b.append(reinterpret_cast<const char *>(p), n);
}

struct Reader {
  const uint8_t *p;
  size_t n, off = 0;
  bool ok = true;
  Reader(const std::string &s)
      : p(reinterpret_cast<const uint8_t *>(s.data())), n(s.size()) {}
  bool need(size_t k) {
    if (off + k > n) ok = false;
    return ok;
  }
  uint16_t u16() {
    if (!need(2)) return 0;
    uint16_t v = (uint16_t(p[off]) << 8) | p[off + 1];
    off += 2;
    return v;
  }
  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v = (v << 8) | p[off + i];
    off += 4;
    return v;
  }
  uint64_t u64() {
    if (!need(8)) return 0;
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v = (v << 8) | p[off + i];
    off += 8;
    return v;
  }
  double f64() {
    uint64_t bits = u64();
    double v;
    memcpy(&v, &bits, 8);
    return v;
  }
  std::string bytes() {
    uint32_t k = u32();
    if (!need(k)) return {};
    std::string s(reinterpret_cast<const char *>(p + off), k);
    off += k;
    return s;
  }
  NodeId id() {
    NodeId v{};
    if (!need(32)) return v;
    memcpy(v.data(), p + off, 32);
    off += 32;
    return v;
  }
};

/* ---------- sockets ---------- */

void set_timeouts(int fd, int ms) {
  timeval tv{ms / 1000, (ms % 1000) * 1000};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

bool write_all(int fd, const char *p, size_t n) {
  while (n) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) return false;
    p += k;
    n -= size_t(k);
  }
  return true;
}

bool read_all(int fd, char *p, size_t n) {
  while (n) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= size_t(k);
  }
  return true;
}

/* Largest acceptable inbound frame. Tensor parts on the data plane are a
 * few MiB (the averager chunks them); anything bigger is a malformed or
 * hostile frame and must not drive a multi-GiB allocation in a handler. */
constexpr size_t kMaxFrame = 64u << 20;

/* frame = u32 length || payload */
bool write_frame(int fd, const std::string &payload) {
  if (payload.size() > kMaxFrame) return false;
  std::string hdr;
  put_u32(hdr, uint32_t(payload.size()));
  return write_all(fd, hdr.data(), 4) &&
         write_all(fd, payload.data(), payload.size());
}

bool read_frame(int fd, std::string *out, size_t max_len = kMaxFrame) {
  char hdr[4];
  if (!read_all(fd, hdr, 4)) return false;
  uint32_t len = 0;
  for (int i = 0; i < 4; ++i) len = (len << 8) | uint8_t(hdr[i]);
  if (len > max_len) return false;
  out->resize(len);
  return read_all(fd, out->data(), len);
}

int connect_to(const char *host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) return -1;
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd >= 0) {
    set_timeouts(fd, timeout_ms);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    if (connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
      close(fd);
      fd = -1;
    }
  }
  freeaddrinfo(res);
  return fd;
}

/* ---------- Kademlia routing ---------- */

struct PeerInfo {
  NodeId id{};
  std::string host;
  uint16_t port = 0;  // 0 = client-mode peer, not routable
};

NodeId xor_dist(const NodeId &a, const NodeId &b) {
  NodeId d;
  for (int i = 0; i < 32; ++i) d[i] = a[i] ^ b[i];
  return d;
}

/* index of the first set bit (0 = most significant); 256 if equal */
int bucket_index(const NodeId &d) {
  for (int i = 0; i < 32; ++i)
    if (d[i])
      for (int b = 7; b >= 0; --b)
        if (d[i] & (1 << b)) return i * 8 + (7 - b);
  return 256;
}

class RoutingTable {
 public:
  explicit RoutingTable(const NodeId &self) : self_(self) {}

  void update(const PeerInfo &peer) {
    if (peer.port == 0 || peer.id == self_) return;  // unroutable / self
    int idx = bucket_index(xor_dist(self_, peer.id));
    if (idx >= 256) return;
    std::lock_guard<std::mutex> g(mu_);
    auto &bucket = buckets_[idx];
    for (auto it = bucket.begin(); it != bucket.end(); ++it)
      if (it->id == peer.id) {
        bucket.erase(it);
        break;
      }
    bucket.push_front(peer);               // most-recently-seen first
    if (bucket.size() > kBucketSize) bucket.pop_back();
  }

  void remove(const NodeId &id) {
    int idx = bucket_index(xor_dist(self_, id));
    if (idx >= 256) return;
    std::lock_guard<std::mutex> g(mu_);
    auto &bucket = buckets_[idx];
    for (auto it = bucket.begin(); it != bucket.end(); ++it)
      if (it->id == id) {
        bucket.erase(it);
        return;
      }
  }

  std::vector<PeerInfo> closest(const NodeId &target, size_t k) const {
    std::vector<PeerInfo> all;
    {
      std::lock_guard<std::mutex> g(mu_);
      for (const auto &b : buckets_) all.insert(all.end(), b.begin(), b.end());
    }
    std::sort(all.begin(), all.end(),
              [&](const PeerInfo &x, const PeerInfo &y) {
                return xor_dist(x.id, target) < xor_dist(y.id, target);
              });
    if (all.size() > k) all.resize(k);
    return all;
  }

  std::vector<PeerInfo> dump() const {
    std::vector<PeerInfo> all;
    std::lock_guard<std::mutex> g(mu_);
    for (const auto &b : buckets_) all.insert(all.end(), b.begin(), b.end());
    return all;
  }

 private:
  NodeId self_;
  mutable std::mutex mu_;
  std::deque<PeerInfo> buckets_[256];
};

/* ---------- record store ---------- */

struct Record {
  std::string value;
  double expiration;
};

class RecordStore {
 public:
  /* Abuse bounds: any peer that can reach the node can issue STOREs, so
   * the store caps record size, subkeys per key, distinct keys, and TTL —
   * a flood fills the caps and stops instead of exhausting memory. */
  static constexpr size_t kMaxValueBytes = 1u << 20;
  static constexpr size_t kMaxSubkeyBytes = 1024;
  static constexpr size_t kMaxSubkeysPerKey = 4096;
  /* Per-writer quota inside a key: subkeys carry their owner public key
   * as an "[owner:<hex>]" suffix (swarm/dht.py); one hostile writer can
   * then fill at most this many slots of a key instead of the whole
   * kMaxSubkeysPerKey — honest announces keep landing under a flood
   * (VERDICT r2 weak #5). Unowned subkeys share one "" bucket. */
  static constexpr size_t kMaxSubkeysPerOwner = 256;
  static constexpr size_t kMaxKeys = 1u << 16;
  static constexpr double kMaxTtlSeconds = 24 * 3600.0;

  /* "...[owner:<hex>]" suffix of a wire subkey, or "" (matches
   * dalle_tpu.swarm.dht's owner marker). */
  static std::string owner_of(const std::string &subkey) {
    static const std::string kOpen = "[owner:", kClose = "]";
    if (subkey.size() < kOpen.size() + kClose.size() ||
        subkey.compare(subkey.size() - 1, 1, kClose) != 0)
      return {};
    size_t at = subkey.rfind(kOpen);
    if (at == std::string::npos) return {};
    return subkey.substr(at + kOpen.size(),
                         subkey.size() - 1 - at - kOpen.size());
  }

  /* Newest expiration wins per (key, subkey) — hivemind's freshness rule.
   * Returns false when a bound rejects the record. */
  bool put(const NodeId &key, const std::string &subkey,
           const std::string &value, double expiration) {
    if (value.size() > kMaxValueBytes || subkey.size() > kMaxSubkeyBytes)
      return false;
    std::lock_guard<std::mutex> g(mu_);
    double t = now_unix();
    if (expiration < t) return false;
    if (expiration > t + kMaxTtlSeconds) expiration = t + kMaxTtlSeconds;
    auto kit = data_.find(key);
    if (kit == data_.end() && data_.size() >= kMaxKeys) {
      gc_locked();
      if (data_.size() >= kMaxKeys) return false;
    }
    /* The per-owner quota applies only to subkeys that CARRY an owner
     * marker: in a validated swarm every honest subkey is owner-marked
     * (dht.py wraps them), so a hostile identity caps out at
     * kMaxSubkeysPerOwner while honest writers keep landing. Unmarked
     * subkeys (open/test swarms with no signature validator) see only
     * the per-key cap — without identities there is nothing to
     * attribute a flood to anyway. */
    bool owned = !owner_of(subkey).empty();
    auto over = [&] {
      return data_[key].size() >= kMaxSubkeysPerKey ||
             (owned &&
              owner_count_locked(key, subkey) >= kMaxSubkeysPerOwner);
    };
    bool is_new = data_[key].find(subkey) == data_[key].end();
    if (is_new && over()) {
      gc_locked();  /* expired entries may be holding the caps */
      is_new = data_[key].find(subkey) == data_[key].end();
      if (is_new && over()) return false;
    }
    auto &slot = data_[key][subkey];
    if (expiration >= slot.expiration) slot = {value, expiration};
    return true;
  }

  std::map<std::string, Record> get(const NodeId &key) {
    std::lock_guard<std::mutex> g(mu_);
    gc_locked();
    auto it = data_.find(key);
    if (it == data_.end()) return {};
    return it->second;
  }

 private:
  size_t owner_count_locked(const NodeId &key, const std::string &subkey) {
    auto it = data_.find(key);
    if (it == data_.end()) return 0;
    const std::string owner = owner_of(subkey);
    size_t n = 0;
    for (const auto &kv : it->second)
      if (owner_of(kv.first) == owner) ++n;
    return n;
  }

  void gc_locked() {
    double t = now_unix();
    for (auto it = data_.begin(); it != data_.end();) {
      auto &subs = it->second;
      for (auto s = subs.begin(); s != subs.end();)
        s = (s->second.expiration < t) ? subs.erase(s) : std::next(s);
      it = subs.empty() ? data_.erase(it) : std::next(it);
    }
  }
  std::mutex mu_;
  std::map<NodeId, std::map<std::string, Record>> data_;
};

}  // namespace

/* ---------- the node ---------- */

struct SwarmNode {
  NodeId id{};
  std::string host;
  int listen_port = 0;
  bool client_mode = false;
  int listen_fd = -1;
  std::atomic<bool> running{true};
  std::atomic<int> timeout_ms{5000};
  std::thread acceptor;
  std::atomic<int> live_handlers{0};

  RoutingTable rt;
  RecordStore store;

  /* data plane: per-tag FIFO queues */
  std::mutex msg_mu;
  std::condition_variable msg_cv;
  std::map<uint64_t, std::deque<std::string>> msgs;

  /* mailbox: TTL'd single-slot entries served to remote FETCHes */
  struct MailEntry {
    std::string payload;
    double expiration;
  };
  std::mutex mail_mu;
  std::map<uint64_t, MailEntry> mailbox;

  void mailbox_gc_locked() {
    double t = now_unix();
    for (auto it = mailbox.begin(); it != mailbox.end();)
      it = (it->second.expiration < t) ? mailbox.erase(it) : std::next(it);
  }

  /* -- client connection pool: one persistent socket per endpoint instead
   * of a TCP connect per RPC (VERDICT r2: per-RPC connects pay a round
   * trip per message on real links). -- */
  static constexpr size_t kPoolPerEndpoint = 4, kPoolTotal = 64;
  std::mutex pool_mu;
  std::map<std::pair<std::string, int>, std::vector<int>> pool;
  size_t pooled = 0;

  int pool_acquire(const std::string &h, int p) {
    std::lock_guard<std::mutex> g(pool_mu);
    auto it = pool.find({h, p});
    if (it == pool.end() || it->second.empty()) return -1;
    int fd = it->second.back();
    it->second.pop_back();
    --pooled;
    return fd;
  }

  void pool_release(const std::string &h, int p, int fd, bool ok) {
    if (!ok || !running.load()) {
      if (fd >= 0) close(fd);
      return;
    }
    std::lock_guard<std::mutex> g(pool_mu);
    auto &v = pool[{h, p}];
    if (v.size() >= kPoolPerEndpoint || pooled >= kPoolTotal) {
      close(fd);
      return;
    }
    v.push_back(fd);
    ++pooled;
  }

  void pool_clear() {
    std::lock_guard<std::mutex> g(pool_mu);
    for (auto &kv : pool)
      for (int fd : kv.second) close(fd);
    pool.clear();
    pooled = 0;
  }

  /* -- relay server state: attachments from client-mode peers -- */
  struct Attachment {
    int fd = -1;
    std::shared_ptr<std::mutex> write_mu;
  };
  std::mutex att_mu;
  std::map<NodeId, Attachment> attachments;

  /* pending relayed fetches awaiting a kRelayReply from an attachment */
  struct PendingFetch {
    std::mutex mu;
    std::condition_variable cv;
    bool done = false, hit = false;
    std::string payload;
  };
  std::mutex pend_mu;
  std::map<uint64_t, std::shared_ptr<PendingFetch>> pending;
  std::atomic<uint64_t> next_req_id{1};

  /* -- relay client state: this node's own attachment to a relay -- */
  std::mutex my_relay_mu;
  int my_relay_fd = -1;
  std::thread my_relay_reader;

  /* -- hole-punched direct links (DHT-coordinated TCP hole punch; the
   * relay stays the fallback). Deterministic roles avoid a tie-break:
   * the peer with the SMALLER node id dials, the larger one accepts. -- */
  struct DirectLink {
    int fd = -1;
    std::shared_ptr<std::mutex> write_mu;
  };
  std::mutex dl_mu;
  std::map<NodeId, DirectLink> direct_links;
  std::map<NodeId, int> punch_sockets;      /* prepared, pre-connect */
  /* host as observed by the relay we attached to (the server-reflexive
   * address a NAT'd peer must advertise for punching); empty until the
   * first kAttachOk carries it */
  std::mutex obs_mu;
  std::string observed_host;
  /* relay traffic served BY this node (the relay role): lets tests and
   * operators observe direct links actually bypassing the relay */
  std::atomic<uint64_t> relay_served{0};

  /* set of inbound handler fds, so destroy() can unblock idle readers */
  std::mutex hfd_mu;
  std::set<int> handler_fds;

  explicit SwarmNode(const NodeId &id_) : id(id_), rt(id_) {}

  std::string header() const {
    std::string h;
    h.append(reinterpret_cast<const char *>(id.data()), 32);
    put_u16(h, client_mode ? 0 : uint16_t(listen_port));
    return h;
  }

  /* True if an idle pooled socket must not carry a new request: the peer
   * closed it while pooled (FIN pending / error), or it has leftover
   * unread bytes (desynced reply stream). */
  static bool sock_stale(int fd) {
    char b;
    ssize_t k = ::recv(fd, &b, 1, MSG_PEEK | MSG_DONTWAIT);
    if (k >= 0) return true;  /* 0 = EOF; >0 = stray bytes */
    return errno != EAGAIN && errno != EWOULDBLOCK;
  }

  /* Pure reads may be resent after a lost reply (a duplicate kPing /
   * kFindNode / kFetch changes no peer state); mutating requests may NOT
   * — kMsg/kStore/kRelaySend enqueue frames the all-reduce part exchange
   * does not de-duplicate (ADVICE r3). */
  static bool idempotent_type(uint8_t t) {
    return t == kPing || t == kFindNode || t == kFindValue ||
           t == kFetch || t == kRelayFetch;
  }

  /* Build request = type || header || body, exchange over a POOLED
   * connection (one persistent socket per endpoint). A resend of a
   * mutating request is safe ONLY while the server cannot have acted on
   * it: stale pooled sockets are filtered by a pre-write probe; a failed
   * (hence at most partial) write leaves the server a truncated frame it
   * discards, so that falls through to a fresh connect; once write_frame
   * has returned true, a read failure retries only idempotent_type()
   * requests — for mutating ones it is a HARD failure, never resent.
   * timeout_override_ms > 0 applies to this call only. */
  bool rpc(const std::string &host_, int port_, uint8_t type,
           const std::string &body, std::string *reply,
           int timeout_override_ms = 0) {
    int ms = timeout_override_ms > 0 ? timeout_override_ms
                                     : timeout_ms.load();
    std::string req;
    req.push_back(char(type));
    req += header();
    req += body;
    if (req.size() > kMaxFrame) return false;  /* doomed: keep the pool */

    int fd;
    while ((fd = pool_acquire(host_, port_)) >= 0) {
      if (sock_stale(fd)) {
        close(fd);
        continue;  /* try the next pooled fd for this endpoint */
      }
      set_timeouts(fd, ms);
      if (!write_frame(fd, req)) {
        close(fd);
        break;  /* request not delivered: safe to go fresh below */
      }
      reply->clear();
      if (read_frame(fd, reply) && !reply->empty()) {
        pool_release(host_, port_, fd, true);
        return true;
      }
      close(fd);
      if (!idempotent_type(type)) return false;  /* may have been acted on */
      break;  /* lost reply on a pure read: harmless to re-ask fresh */
    }

    fd = connect_to(host_.c_str(), port_, ms);
    if (fd < 0) return false;
    reply->clear();
    bool ok = write_frame(fd, req) && read_frame(fd, reply) &&
              !reply->empty();
    pool_release(host_, port_, fd, ok);
    return ok;
  }

  void note_peer(const PeerInfo &p) { rt.update(p); }

  /* Handle one inbound request; returns the reply frame payload. */
  std::string handle(const std::string &req, const std::string &peer_host) {
    Reader r(req);
    if (!r.need(1)) return {};
    uint8_t type = r.p[r.off];
    r.off += 1;
    PeerInfo sender{r.id(), peer_host, r.u16()};
    if (!r.ok) return {};
    note_peer(sender);

    std::string rep;
    switch (type) {
      case kPing: {
        rep.push_back(char(kPong));
        rep += header();
        break;
      }
      case kStore: {
        NodeId key = r.id();
        std::string subkey = r.bytes(), value = r.bytes();
        double exp = r.f64();
        if (!r.ok) return {};
        if (store.put(key, subkey, value, exp))
          rep.push_back(char(kStoreOk));
        else
          rep.push_back(char(0));  /* bound rejected the record */
        break;
      }
      case kFindNode: {
        NodeId target = r.id();
        if (!r.ok) return {};
        rep.push_back(char(kNodes));
        append_nodes(rep, rt.closest(target, kBucketSize));
        break;
      }
      case kFindValue: {
        NodeId key = r.id();
        if (!r.ok) return {};
        auto found = store.get(key);
        if (!found.empty()) {
          rep.push_back(char(kValue));
          put_u32(rep, uint32_t(found.size()));
          for (auto &kv : found) {
            put_bytes(rep, reinterpret_cast<const uint8_t *>(kv.first.data()),
                      kv.first.size());
            put_bytes(rep,
                      reinterpret_cast<const uint8_t *>(kv.second.value.data()),
                      kv.second.value.size());
            put_f64(rep, kv.second.expiration);
          }
        } else {
          rep.push_back(char(kNodes));
          append_nodes(rep, rt.closest(key, kBucketSize));
        }
        break;
      }
      case kMsg: {
        uint64_t tag = r.u64();
        std::string payload = r.bytes();
        if (!r.ok) return {};
        {
          std::lock_guard<std::mutex> g(msg_mu);
          msgs[tag].push_back(std::move(payload));
        }
        msg_cv.notify_all();
        rep.push_back(char(kMsgOk));
        break;
      }
      case kFetch: {
        uint64_t tag = r.u64();
        if (!r.ok) return {};
        std::lock_guard<std::mutex> g(mail_mu);
        mailbox_gc_locked();
        auto it = mailbox.find(tag);
        if (it == mailbox.end()) {
          rep.push_back(char(kFetchMiss));
        } else {
          rep.push_back(char(kFetchHit));
          put_bytes(rep,
                    reinterpret_cast<const uint8_t *>(it->second.payload.data()),
                    it->second.payload.size());
        }
        break;
      }
      case kRelaySend: {
        NodeId target = r.id();
        uint64_t tag = r.u64();
        std::string payload = r.bytes();
        if (!r.ok) return {};
        relay_served.fetch_add(1);
        std::string fwd;
        fwd.push_back(char(kMsg));
        put_u64(fwd, tag);
        put_bytes(fwd, reinterpret_cast<const uint8_t *>(payload.data()),
                  payload.size());
        rep.push_back(forward_to_attachment(target, fwd) ? char(kMsgOk)
                                                         : char(kRelayMiss));
        break;
      }
      case kRelayFetch: {
        NodeId target = r.id();
        uint64_t tag = r.u64();
        if (!r.ok) return {};
        relay_served.fetch_add(1);
        uint64_t rid = next_req_id.fetch_add(1);
        auto pf = std::make_shared<PendingFetch>();
        {
          std::lock_guard<std::mutex> g(pend_mu);
          pending[rid] = pf;
        }
        std::string fwd;
        fwd.push_back(char(kFetch));
        put_u64(fwd, rid);
        put_u64(fwd, tag);
        bool sent = forward_to_attachment(target, fwd);
        bool hit = false;
        std::string payload;
        if (sent) {
          std::unique_lock<std::mutex> lk(pf->mu);
          pf->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms.load()),
                          [&] { return pf->done; });
          hit = pf->done && pf->hit;
          payload = std::move(pf->payload);
        }
        {
          std::lock_guard<std::mutex> g(pend_mu);
          pending.erase(rid);
        }
        if (hit) {
          rep.push_back(char(kFetchHit));
          put_bytes(rep, reinterpret_cast<const uint8_t *>(payload.data()),
                    payload.size());
        } else {
          rep.push_back(char(sent ? kFetchMiss : kRelayMiss));
        }
        break;
      }
      default:
        return {};
    }
    return rep;
  }

  /* Write one frame down the persistent attachment of `target` (under its
   * write mutex). Returns false when the target is not attached or the
   * write fails (the attachment is then dropped). */
  bool forward_to_attachment(const NodeId &target, const std::string &frame) {
    int afd = -1;
    std::shared_ptr<std::mutex> wmu;
    {
      std::lock_guard<std::mutex> g(att_mu);
      auto it = attachments.find(target);
      if (it != attachments.end()) {
        afd = it->second.fd;
        wmu = it->second.write_mu;
      }
    }
    if (afd < 0) return false;
    bool ok;
    {
      std::lock_guard<std::mutex> g(*wmu);
      ok = write_frame(afd, frame);
    }
    if (!ok) {
      std::lock_guard<std::mutex> g(att_mu);
      auto it = attachments.find(target);
      if (it != attachments.end() && it->second.fd == afd) {
        shutdown(afd, SHUT_RDWR);
        attachments.erase(it);
      }
    }
    return ok;
  }

  /* Serve an inbound connection that upgraded itself into a relay
   * attachment: register it, then pump kRelayReply frames until EOF. */
  void serve_attachment(int cfd, const NodeId &peer,
                        const std::string &peer_host) {
    auto wmu = std::make_shared<std::mutex>();
    {
      std::lock_guard<std::mutex> g(att_mu);
      auto old = attachments.find(peer);
      if (old != attachments.end()) shutdown(old->second.fd, SHUT_RDWR);
      attachments[peer] = {cfd, wmu};
    }
    {
      std::lock_guard<std::mutex> g(*wmu);
      /* kAttachOk carries the client's address AS THE RELAY SEES IT —
       * the server-reflexive host a NAT'd peer must advertise when
       * coordinating a hole punch (its local bind address is private) */
      std::string ok(1, char(kAttachOk));
      put_bytes(ok, reinterpret_cast<const uint8_t *>(peer_host.data()),
                peer_host.size());
      if (!write_frame(cfd, ok)) {
        /* deregister before the caller closes cfd — a stale map entry
         * would later inject frames into (and then kill) whatever
         * unrelated connection reuses this fd number */
        std::lock_guard<std::mutex> g2(att_mu);
        auto it = attachments.find(peer);
        if (it != attachments.end() && it->second.fd == cfd)
          attachments.erase(it);
        return;
      }
    }
    /* attachments idle indefinitely (kernel keepalive handles dead NATs;
     * destroy() shuts the fd down to unblock this read) */
    set_timeouts(cfd, 0);
    int one = 1;
    setsockopt(cfd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof one);
    std::string fr;
    while (running.load() && read_frame(cfd, &fr)) {
      Reader r(fr);
      if (!r.need(1)) break;
      uint8_t t = r.p[0];
      r.off = 1;
      if (t != kRelayReply) continue;
      uint64_t rid = r.u64();
      uint8_t hit = 0;
      if (r.need(1)) {
        hit = r.p[r.off];
        r.off += 1;
      }
      std::string payload = r.bytes();
      if (!r.ok) continue;
      std::shared_ptr<PendingFetch> pf;
      {
        std::lock_guard<std::mutex> g(pend_mu);
        auto it = pending.find(rid);
        if (it != pending.end()) pf = it->second;
      }
      if (pf) {
        std::lock_guard<std::mutex> g(pf->mu);
        pf->done = true;
        pf->hit = hit != 0;
        pf->payload = std::move(payload);
        pf->cv.notify_all();
      }
    }
    std::lock_guard<std::mutex> g(att_mu);
    auto it = attachments.find(peer);
    if (it != attachments.end() && it->second.fd == cfd)
      attachments.erase(it);
  }

  /* ---- hole-punched direct links ---------------------------------- */

  void drop_direct(const NodeId &peer, int expect_fd) {
    std::lock_guard<std::mutex> g(dl_mu);
    auto it = direct_links.find(peer);
    if (it != direct_links.end() && it->second.fd == expect_fd) {
      shutdown(expect_fd, SHUT_RDWR);
      direct_links.erase(it);
    }
  }

  /* Pump a punched connection: symmetric vocabulary with the relay
   * attachment — inbound kMsg -> recv queues, inbound kFetch answered
   * from the local mailbox via kRelayReply, inbound kRelayReply resolves
   * this node's own pending direct fetches. Writes from other threads
   * (direct_send / direct_fetch) share the link's write mutex. */
  void serve_direct(int fd, NodeId peer, std::shared_ptr<std::mutex> wmu) {
    std::string fr;
    while (running.load() && read_frame(fd, &fr)) {
      Reader r(fr);
      if (!r.need(1)) break;
      uint8_t t = r.p[0];
      r.off = 1;
      if (t == kMsg) {
        uint64_t tag = r.u64();
        std::string payload = r.bytes();
        if (!r.ok) continue;
        {
          std::lock_guard<std::mutex> g(msg_mu);
          msgs[tag].push_back(std::move(payload));
        }
        msg_cv.notify_all();
      } else if (t == kFetch) {
        uint64_t rid = r.u64(), tag = r.u64();
        if (!r.ok) continue;
        std::string rep;
        rep.push_back(char(kRelayReply));
        put_u64(rep, rid);
        {
          std::lock_guard<std::mutex> g(mail_mu);
          mailbox_gc_locked();
          auto it = mailbox.find(tag);
          if (it == mailbox.end()) {
            rep.push_back(char(0));
            put_bytes(rep, nullptr, 0);
          } else {
            rep.push_back(char(1));
            put_bytes(rep, reinterpret_cast<const uint8_t *>(
                               it->second.payload.data()),
                      it->second.payload.size());
          }
        }
        std::lock_guard<std::mutex> g(*wmu);
        if (!write_frame(fd, rep)) break;
      } else if (t == kRelayReply) {
        uint64_t rid = r.u64();
        uint8_t hit = 0;
        if (r.need(1)) {
          hit = r.p[r.off];
          r.off += 1;
        }
        std::string payload = r.bytes();
        if (!r.ok) continue;
        std::shared_ptr<PendingFetch> pf;
        {
          std::lock_guard<std::mutex> g(pend_mu);
          auto it = pending.find(rid);
          if (it != pending.end()) pf = it->second;
        }
        if (pf) {
          std::lock_guard<std::mutex> g(pf->mu);
          pf->done = true;
          pf->hit = hit != 0;
          pf->payload = std::move(payload);
          pf->cv.notify_all();
        }
      }
    }
    drop_direct(peer, fd);
    close(fd);
  }

  bool register_direct(int fd, const NodeId &peer) {
    auto wmu = std::make_shared<std::mutex>();
    /* reads idle indefinitely (destroy() unblocks via handler_fds);
     * WRITES are bounded so a stalled peer cannot park a sender holding
     * the link's write mutex forever, and TCP_USER_TIMEOUT makes a
     * half-open link (NAT mapping died, no RST) error out instead of
     * buffering sends into the void for hours */
    timeval tv{0, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    timeval stv{30, 0};
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &stv, sizeof stv);
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof one);
#ifdef TCP_USER_TIMEOUT
    unsigned int ut = 30000;
    setsockopt(fd, IPPROTO_TCP, TCP_USER_TIMEOUT, &ut, sizeof ut);
#endif
    {
      std::lock_guard<std::mutex> g(dl_mu);
      if (!running.load()) return false;  /* destroy() already tearing down */
      auto old = direct_links.find(peer);
      if (old != direct_links.end()) shutdown(old->second.fd, SHUT_RDWR);
      direct_links[peer] = {fd, wmu};
    }
    /* same lifecycle as inbound handlers: detached + live_handlers +
     * handler_fds (destroy() shuts the fd to unblock the idle read and
     * waits for the counter) — no unbounded thread vector */
    live_handlers.fetch_add(1);
    {
      std::lock_guard<std::mutex> g(hfd_mu);
      handler_fds.insert(fd);
    }
    std::thread([this, fd, peer, wmu] {
      try {
        serve_direct(fd, peer, wmu);
      } catch (...) {
      }
      {
        std::lock_guard<std::mutex> g(hfd_mu);
        handler_fds.erase(fd);
      }
      live_handlers.fetch_sub(1);
    }).detach();
    return true;
  }

  /* kMsg straight down the punched link; false -> caller falls back to
   * the relay (and the dead link is dropped). */
  bool direct_send(const NodeId &peer, uint64_t tag,
                   const uint8_t *payload, size_t len) {
    int fd = -1;
    std::shared_ptr<std::mutex> wmu;
    {
      std::lock_guard<std::mutex> g(dl_mu);
      auto it = direct_links.find(peer);
      if (it == direct_links.end()) return false;
      fd = it->second.fd;
      wmu = it->second.write_mu;
    }
    std::string frame;
    frame.push_back(char(kMsg));
    put_u64(frame, tag);
    put_bytes(frame, payload, len);
    bool ok;
    {
      std::lock_guard<std::mutex> g(*wmu);
      ok = write_frame(fd, frame);
    }
    if (!ok) drop_direct(peer, fd);
    return ok;
  }

  /* Mailbox fetch over the punched link (same rid/pending machinery as
   * relayed fetches). hit=false with ok=true means a clean miss. */
  bool direct_fetch(const NodeId &peer, uint64_t tag, int tmo_ms,
                    bool *hit, std::string *payload) {
    int fd = -1;
    std::shared_ptr<std::mutex> wmu;
    {
      std::lock_guard<std::mutex> g(dl_mu);
      auto it = direct_links.find(peer);
      if (it == direct_links.end()) return false;
      fd = it->second.fd;
      wmu = it->second.write_mu;
    }
    uint64_t rid = next_req_id.fetch_add(1);
    auto pf = std::make_shared<PendingFetch>();
    {
      std::lock_guard<std::mutex> g(pend_mu);
      pending[rid] = pf;
    }
    std::string frame;
    frame.push_back(char(kFetch));
    put_u64(frame, rid);
    put_u64(frame, tag);
    bool ok;
    {
      std::lock_guard<std::mutex> g(*wmu);
      ok = write_frame(fd, frame);
    }
    if (ok) {
      std::unique_lock<std::mutex> lk(pf->mu);
      pf->cv.wait_for(lk, std::chrono::milliseconds(tmo_ms),
                      [&] { return pf->done; });
      if (!pf->done) {
        /* the peer did not answer within the caller's budget: treat the
         * link as dead (a live peer answers misses immediately), report
         * an authoritative miss, and let later calls use the relay —
         * falling through to a relay RPC here would silently DOUBLE the
         * caller's timeout */
        drop_direct(peer, fd);
        *hit = false;
        *payload = {};
      } else {
        *hit = pf->hit;
        *payload = std::move(pf->payload);
      }
      ok = true;
    } else {
      drop_direct(peer, fd);
    }
    {
      std::lock_guard<std::mutex> g(pend_mu);
      pending.erase(rid);
    }
    return ok;
  }

  static void append_nodes(std::string &rep,
                           const std::vector<PeerInfo> &nodes) {
    put_u32(rep, uint32_t(nodes.size()));
    for (const auto &n : nodes) {
      rep.append(reinterpret_cast<const char *>(n.id.data()), 32);
      put_bytes(rep, reinterpret_cast<const uint8_t *>(n.host.data()),
                n.host.size());
      put_u16(rep, n.port);
    }
  }

  static std::vector<PeerInfo> parse_nodes(Reader &r) {
    std::vector<PeerInfo> out;
    uint32_t n = r.u32();
    for (uint32_t i = 0; i < n && r.ok; ++i) {
      PeerInfo p;
      p.id = r.id();
      p.host = r.bytes();
      p.port = r.u16();
      if (r.ok) out.push_back(std::move(p));
    }
    return out;
  }

  /* Iterative node lookup (Kademlia): returns up to k closest live peers.
   * When collect_values != nullptr, FIND_VALUE is used and every VALUE
   * reply is merged into *collect_values (latest expiration wins). */
  std::vector<PeerInfo> lookup(const NodeId &target,
                               std::map<std::string, Record> *collect_values) {
    auto cmp = [&](const PeerInfo &x, const PeerInfo &y) {
      return xor_dist(x.id, target) < xor_dist(y.id, target);
    };
    std::vector<PeerInfo> shortlist = rt.closest(target, kBucketSize);
    std::set<NodeId> queried, known;
    for (auto &p : shortlist) known.insert(p.id);

    while (running.load()) {
      /* pick up to alpha unqueried peers nearest the target */
      std::sort(shortlist.begin(), shortlist.end(), cmp);
      std::vector<PeerInfo> batch;
      for (const auto &p : shortlist) {
        if (queried.count(p.id)) continue;
        batch.push_back(p);
        if (batch.size() >= kAlpha) break;
      }
      if (batch.empty()) break;

      bool learned = false;
      for (const auto &p : batch) {
        queried.insert(p.id);
        std::string body(reinterpret_cast<const char *>(target.data()), 32);
        std::string reply;
        uint8_t q = collect_values ? kFindValue : kFindNode;
        if (!rpc(p.host, p.port, q, body, &reply)) {
          rt.remove(p.id);  // unresponsive peers drop out (elasticity)
          continue;
        }
        Reader r(reply);
        if (!r.need(1)) continue;
        uint8_t rtype = r.p[r.off];
        r.off += 1;
        if (rtype == kValue && collect_values) {
          uint32_t cnt = r.u32();
          for (uint32_t i = 0; i < cnt && r.ok; ++i) {
            std::string sk = r.bytes(), val = r.bytes();
            double exp = r.f64();
            if (!r.ok) break;
            auto it = collect_values->find(sk);
            if (it == collect_values->end() || exp >= it->second.expiration)
              (*collect_values)[sk] = {val, exp};
          }
        } else if (rtype == kNodes) {
          for (auto &n : parse_nodes(r)) {
            note_peer(n);
            if (known.insert(n.id).second) {
              shortlist.push_back(n);
              learned = true;
            }
          }
        }
      }
      if (!learned && queried.size() >= std::min(shortlist.size(),
                                                 size_t(kBucketSize)))
        break;
    }
    std::sort(shortlist.begin(), shortlist.end(), cmp);
    std::vector<PeerInfo> live;
    for (const auto &p : shortlist) {
      if (queried.count(p.id) && live.size() < kBucketSize) live.push_back(p);
      /* peers that failed rpc were removed from rt but may linger in
       * shortlist; they were never re-added, so keep only queried ones */
    }
    if (live.empty()) live = shortlist;  // nothing queried: fall back
    if (live.size() > kBucketSize) live.resize(kBucketSize);
    return live;
  }

  void serve() {
    while (running.load()) {
      sockaddr_in peer{};
      socklen_t plen = sizeof peer;
      int cfd = accept(listen_fd, reinterpret_cast<sockaddr *>(&peer), &plen);
      if (cfd < 0) {
        if (!running.load()) break;
        continue;
      }
      char ip[INET_ADDRSTRLEN] = "127.0.0.1";
      inet_ntop(AF_INET, &peer.sin_addr, ip, sizeof ip);
      set_timeouts(cfd, timeout_ms.load());
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      live_handlers.fetch_add(1);
      {
        std::lock_guard<std::mutex> g(hfd_mu);
        handler_fds.insert(cfd);
      }
      std::thread([this, cfd, host = std::string(ip)] {
        try {
          /* serve MANY requests per connection (the client side pools
           * them); a kRelayAttach upgrades the connection into a
           * persistent relay attachment instead */
          std::string req;
          while (running.load() && read_frame(cfd, &req)) {
            if (!req.empty() && uint8_t(req[0]) == kRelayAttach) {
              Reader r(req);
              r.off = 1;
              PeerInfo sender{r.id(), host, r.u16()};
              if (r.ok) serve_attachment(cfd, sender.id, host);
              break;
            }
            std::string rep = handle(req, host);
            if (rep.empty() || !write_frame(cfd, rep)) break;
            /* pooled client connections may idle between RPCs */
            set_timeouts(cfd, kIdleMs);
          }
        } catch (...) {
          /* bad_alloc on a hostile frame etc. must not terminate() */
        }
        close(cfd);
        {
          std::lock_guard<std::mutex> g(hfd_mu);
          handler_fds.erase(cfd);
        }
        live_handlers.fetch_sub(1);
      }).detach();
    }
  }
};

/* ---------- C API ---------- */

extern "C" {

SwarmNode *swarm_node_create(const char *host, int port, const uint8_t id[32],
                             int client_mode) {
  NodeId nid{};
  memcpy(nid.data(), id, 32);
  auto *node = new SwarmNode(nid);
  node->host = host ? host : "127.0.0.1";
  node->client_mode = client_mode != 0;
  if (node->client_mode) return node;

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    delete node;
    return nullptr;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  inet_pton(AF_INET, node->host.c_str(), &addr.sin_addr);
  if (bind(fd, reinterpret_cast<sockaddr *>(&addr), sizeof addr) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    delete node;
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr *>(&addr), &alen);
  node->listen_port = ntohs(addr.sin_port);
  node->listen_fd = fd;
  node->acceptor = std::thread([node] { node->serve(); });
  return node;
}

int swarm_node_port(const SwarmNode *node) { return node->listen_port; }

void swarm_node_set_timeout(SwarmNode *node, int timeout_ms) {
  node->timeout_ms.store(timeout_ms);
}

int swarm_node_bootstrap(SwarmNode *node, const char *host, int port) {
  std::string reply;
  if (!node->rpc(host, port, kPing, "", &reply)) return -1;
  Reader r(reply);
  if (!r.need(1) || r.p[0] != kPong) return -1;
  r.off = 1;
  PeerInfo boot{r.id(), host, r.u16()};
  if (!r.ok) return -1;
  node->note_peer(boot);
  node->lookup(node->id, nullptr);  // iterative self-lookup fills buckets
  return 0;
}

int swarm_node_store(SwarmNode *node, const uint8_t key[32],
                     const uint8_t *subkey, size_t subkey_len,
                     const uint8_t *value, size_t value_len,
                     double expiration) {
  NodeId k{};
  memcpy(k.data(), key, 32);
  std::string sk(reinterpret_cast<const char *>(subkey), subkey_len);
  std::string val(reinterpret_cast<const char *>(value), value_len);
  node->store.put(k, sk, val, expiration);  // local replica

  auto targets = node->lookup(k, nullptr);
  int ok = 0;
  std::string body(reinterpret_cast<const char *>(k.data()), 32);
  put_bytes(body, subkey, subkey_len);
  put_bytes(body, value, value_len);
  put_f64(body, expiration);
  for (const auto &p : targets) {
    std::string reply;
    if (node->rpc(p.host, p.port, kStore, body, &reply) &&
        !reply.empty() && uint8_t(reply[0]) == kStoreOk)
      ++ok;
  }
  return ok;
}

uint8_t *swarm_node_get(SwarmNode *node, const uint8_t key[32],
                        size_t *out_len) {
  NodeId k{};
  memcpy(k.data(), key, 32);
  std::map<std::string, Record> merged;
  double t = now_unix();
  for (auto &kv : node->store.get(k))
    if (kv.second.expiration >= t) merged[kv.first] = kv.second;
  node->lookup(k, &merged);

  std::string out;
  uint32_t cnt = 0;
  std::string entries;
  for (auto &kv : merged) {
    if (kv.second.expiration < t) continue;
    put_bytes(entries, reinterpret_cast<const uint8_t *>(kv.first.data()),
              kv.first.size());
    put_bytes(entries,
              reinterpret_cast<const uint8_t *>(kv.second.value.data()),
              kv.second.value.size());
    put_f64(entries, kv.second.expiration);
    ++cnt;
  }
  if (cnt == 0) return nullptr;
  put_u32(out, cnt);
  out += entries;
  auto *buf = static_cast<uint8_t *>(malloc(out.size()));
  memcpy(buf, out.data(), out.size());
  *out_len = out.size();
  return buf;
}

int swarm_node_send(SwarmNode *node, const char *host, int port, uint64_t tag,
                    const uint8_t *payload, size_t len, int timeout_ms) {
  std::string body;
  put_u64(body, tag);
  put_bytes(body, payload, len);
  std::string reply;
  if (!node->rpc(host, port, kMsg, body, &reply, timeout_ms)) return -1;
  return (!reply.empty() && uint8_t(reply[0]) == kMsgOk) ? 0 : -1;
}

uint8_t *swarm_node_recv(SwarmNode *node, uint64_t tag, int timeout_ms,
                         size_t *out_len) {
  std::unique_lock<std::mutex> lk(node->msg_mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    auto it = node->msgs.find(tag);
    if (it != node->msgs.end() && !it->second.empty()) {
      std::string payload = std::move(it->second.front());
      it->second.pop_front();
      if (it->second.empty()) node->msgs.erase(it);
      lk.unlock();
      auto *buf = static_cast<uint8_t *>(malloc(payload.size()));
      memcpy(buf, payload.data(), payload.size());
      *out_len = payload.size();
      return buf;
    }
    if (node->msg_cv.wait_until(lk, deadline) == std::cv_status::timeout &&
        std::chrono::steady_clock::now() >= deadline)
      return nullptr;
  }
}

int swarm_node_post(SwarmNode *node, uint64_t tag, const uint8_t *payload,
                    size_t len, double expiration) {
  if (len > kMaxFrame) return -1;
  std::lock_guard<std::mutex> g(node->mail_mu);
  node->mailbox_gc_locked();
  node->mailbox[tag] = {std::string(reinterpret_cast<const char *>(payload),
                                    len),
                        expiration};
  return 0;
}

uint8_t *swarm_node_fetch(SwarmNode *node, const char *host, int port,
                          uint64_t tag, int timeout_ms, size_t *out_len) {
  std::string body;
  put_u64(body, tag);
  std::string reply;
  if (!node->rpc(host, port, kFetch, body, &reply, timeout_ms))
    return nullptr;
  Reader r(reply);
  if (!r.need(1) || r.p[0] != kFetchHit) return nullptr;
  r.off = 1;
  std::string payload = r.bytes();
  if (!r.ok) return nullptr;
  auto *buf = static_cast<uint8_t *>(malloc(payload.size()));
  memcpy(buf, payload.data(), payload.size());
  *out_len = payload.size();
  return buf;
}

int swarm_node_attach_relay(SwarmNode *node, const char *host, int port) {
  int fd = connect_to(host, port, node->timeout_ms.load());
  if (fd < 0) return -1;
  std::string req;
  req.push_back(char(kRelayAttach));
  req += node->header();
  std::string reply;
  if (!write_frame(fd, req) || !read_frame(fd, &reply) || reply.empty() ||
      uint8_t(reply[0]) != kAttachOk) {
    close(fd);
    return -1;
  }
  {
    /* the relay's view of our address (server-reflexive host for punch
     * coordination); absent on replies from pre-r4 relays */
    Reader r(reply);
    r.off = 1;
    std::string obs = r.bytes();
    if (r.ok && !obs.empty()) {
      std::lock_guard<std::mutex> g(node->obs_mu);
      node->observed_host = obs;
    }
  }
  set_timeouts(fd, 0);  /* destroy()/re-attach unblocks via shutdown */
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof one);

  std::lock_guard<std::mutex> g(node->my_relay_mu);
  if (node->my_relay_fd >= 0) {
    shutdown(node->my_relay_fd, SHUT_RDWR);
    close(node->my_relay_fd);
  }
  if (node->my_relay_reader.joinable()) node->my_relay_reader.join();
  node->my_relay_fd = fd;
  node->my_relay_reader = std::thread([node, fd] {
    /* pump forwarded frames: kMsg -> recv queues; kFetch -> answer from
     * the local mailbox with kRelayReply over the same connection (this
     * thread is the connection's only writer after attach). */
    std::string fr;
    while (node->running.load() && read_frame(fd, &fr)) {
      Reader r(fr);
      if (!r.need(1)) break;
      uint8_t t = r.p[0];
      r.off = 1;
      if (t == kMsg) {
        uint64_t tag = r.u64();
        std::string payload = r.bytes();
        if (!r.ok) continue;
        {
          std::lock_guard<std::mutex> g2(node->msg_mu);
          node->msgs[tag].push_back(std::move(payload));
        }
        node->msg_cv.notify_all();
      } else if (t == kFetch) {
        uint64_t rid = r.u64(), tag = r.u64();
        if (!r.ok) continue;
        std::string rep;
        rep.push_back(char(kRelayReply));
        put_u64(rep, rid);
        {
          std::lock_guard<std::mutex> g2(node->mail_mu);
          node->mailbox_gc_locked();
          auto it = node->mailbox.find(tag);
          if (it == node->mailbox.end()) {
            rep.push_back(char(0));
            put_bytes(rep, nullptr, 0);
          } else {
            rep.push_back(char(1));
            put_bytes(rep, reinterpret_cast<const uint8_t *>(
                               it->second.payload.data()),
                      it->second.payload.size());
          }
        }
        if (!write_frame(fd, rep)) break;
      }
    }
  });
  return 0;
}

int swarm_node_relay_send(SwarmNode *node, const char *host, int port,
                          const uint8_t target[32], uint64_t tag,
                          const uint8_t *payload, size_t len,
                          int timeout_ms) {
  NodeId tid;
  memcpy(tid.data(), target, 32);
  /* punched direct link first; the relay is the fallback path */
  if (node->direct_send(tid, tag, payload, len)) return 0;
  std::string body(reinterpret_cast<const char *>(target), 32);
  put_u64(body, tag);
  put_bytes(body, payload, len);
  std::string reply;
  if (!node->rpc(host, port, kRelaySend, body, &reply, timeout_ms))
    return -1;
  return (!reply.empty() && uint8_t(reply[0]) == kMsgOk) ? 0 : -1;
}

uint8_t *swarm_node_relay_fetch(SwarmNode *node, const char *host, int port,
                                const uint8_t target[32], uint64_t tag,
                                int timeout_ms, size_t *out_len) {
  NodeId tid;
  memcpy(tid.data(), target, 32);
  {
    bool hit = false;
    std::string payload;
    int tmo = timeout_ms > 0 ? timeout_ms : node->timeout_ms.load();
    if (node->direct_fetch(tid, tag, tmo, &hit, &payload)) {
      if (!hit) return nullptr;  /* clean miss over the direct link */
      auto *buf = static_cast<uint8_t *>(malloc(payload.size()));
      memcpy(buf, payload.data(), payload.size());
      *out_len = payload.size();
      return buf;
    }
  }
  std::string body(reinterpret_cast<const char *>(target), 32);
  put_u64(body, tag);
  std::string reply;
  if (!node->rpc(host, port, kRelayFetch, body, &reply, timeout_ms))
    return nullptr;
  Reader r(reply);
  if (!r.need(1) || r.p[0] != kFetchHit) return nullptr;
  r.off = 1;
  std::string payload = r.bytes();
  if (!r.ok) return nullptr;
  auto *buf = static_cast<uint8_t *>(malloc(payload.size()));
  memcpy(buf, payload.data(), payload.size());
  *out_len = payload.size();
  return buf;
}

/* ---- hole punch C API -------------------------------------------------
 *
 * Protocol (DHT-coordinated TCP hole punch, reference: the libp2p
 * daemon's transport-level hole punching, arguments.py:89-124):
 *
 * 1. both peers call prepare(target): bind a socket (SO_REUSEADDR |
 *    SO_REUSEPORT) to an ephemeral port; the DIALER role (smaller node
 *    id) gets a plain socket, the ACCEPTOR (larger id) a listener.
 *    Each advertises the bound port through the DHT (python side).
 * 2. both call connect(target, other_host, other_port, timeout): the
 *    dialer connect()s in a retry loop FROM its bound port (re-binding
 *    after each refused attempt keeps the NAT mapping alive — the
 *    simultaneous-open path); the acceptor accept()s.
 * 3. both sides exchange kPunchHello || header and verify the peer id
 *    matches the expectation; the socket then becomes a DirectLink that
 *    relayed sends/fetches prefer over the relay.
 */

static int bound_socket(int *out_port, bool listen_too) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
#ifdef SO_REUSEPORT
  setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof one);
#endif
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(uint16_t(*out_port));
  if (bind(fd, reinterpret_cast<sockaddr *>(&addr), sizeof addr) != 0 ||
      (listen_too && listen(fd, 4) != 0)) {
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr *>(&addr), &alen);
  *out_port = ntohs(addr.sin_port);
  return fd;
}

static bool punch_hello(SwarmNode *node, int fd, const NodeId &expect,
                        int timeout_ms) {
  set_timeouts(fd, timeout_ms);
  std::string hello;
  hello.push_back(char(kPunchHello));
  hello += node->header();
  if (!write_frame(fd, hello)) return false;
  std::string got;
  if (!read_frame(fd, &got)) return false;
  Reader r(got);
  if (!r.need(1) || r.p[0] != kPunchHello) return false;
  r.off = 1;
  NodeId peer = r.id();
  return r.ok && peer == expect;
}

int swarm_node_punch_prepare(SwarmNode *node, const uint8_t target[32]) {
  NodeId tid;
  memcpy(tid.data(), target, 32);
  bool dialer = node->id < tid;
  int port = 0;
  int fd = bound_socket(&port, /*listen_too=*/!dialer);
  if (fd < 0) return -1;
  std::lock_guard<std::mutex> g(node->dl_mu);
  auto old = node->punch_sockets.find(tid);
  if (old != node->punch_sockets.end()) close(old->second);
  node->punch_sockets[tid] = fd;
  return port;
}

int swarm_node_punch_connect(SwarmNode *node, const uint8_t target[32],
                             const char *host, int port, int timeout_ms) {
  /* count as a live handler so destroy() (which sets running=false and
   * then waits for the counter) cannot free the node under our feet */
  node->live_handlers.fetch_add(1);
  struct Guard {
    SwarmNode *n;
    ~Guard() { n->live_handlers.fetch_sub(1); }
  } guard{node};
  if (!node->running.load()) return -1;
  NodeId tid;
  memcpy(tid.data(), target, 32);
  bool dialer = node->id < tid;
  int fd = -1;
  {
    std::lock_guard<std::mutex> g(node->dl_mu);
    auto it = node->punch_sockets.find(tid);
    if (it == node->punch_sockets.end()) return -1;
    fd = it->second;
    node->punch_sockets.erase(it);
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  int conn = -1;
  if (dialer) {
    sockaddr_in raddr{};
    raddr.sin_family = AF_INET;
    raddr.sin_port = htons(uint16_t(port));
    if (inet_pton(AF_INET, host, &raddr.sin_addr) != 1) {
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) {
        close(fd);
        return -1;
      }
      raddr.sin_addr =
          reinterpret_cast<sockaddr_in *>(res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    sockaddr_in laddr{};
    socklen_t llen = sizeof laddr;
    getsockname(fd, reinterpret_cast<sockaddr *>(&laddr), &llen);
    int lport = ntohs(laddr.sin_port);
    while (std::chrono::steady_clock::now() < deadline &&
           node->running.load()) {
      set_timeouts(fd, 1000);
      if (connect(fd, reinterpret_cast<sockaddr *>(&raddr),
                  sizeof raddr) == 0) {
        conn = fd;
        fd = -1;
        break;
      }
      /* refused/timed out: a fresh socket re-bound to the SAME port
       * keeps the advertised mapping while we retry */
      close(fd);
      std::this_thread::sleep_for(std::chrono::milliseconds(150));
      int p = lport;
      fd = bound_socket(&p, false);
      if (fd < 0) return -1;
    }
  } else {
    while (std::chrono::steady_clock::now() < deadline &&
           node->running.load()) {
      set_timeouts(fd, 1000);
      sockaddr_in who{};
      socklen_t wlen = sizeof who;
      int c = accept(fd, reinterpret_cast<sockaddr *>(&who), &wlen);
      if (c >= 0) {
        conn = c;
        break;
      }
    }
    close(fd);
    fd = -1;
  }
  if (fd >= 0) close(fd);
  if (conn < 0) return -1;
  int remain = int(std::chrono::duration_cast<std::chrono::milliseconds>(
                       deadline - std::chrono::steady_clock::now())
                       .count());
  if (!punch_hello(node, conn, tid, std::max(1000, remain)) ||
      !node->register_direct(conn, tid)) {
    close(conn);
    return -1;
  }
  return 0;
}

/* Host as observed by this node's relay (server-reflexive address for
 * punch coordination). malloc'd string or NULL if no relay reported one. */
uint8_t *swarm_node_observed_host(SwarmNode *node, size_t *out_len) {
  std::lock_guard<std::mutex> g(node->obs_mu);
  if (node->observed_host.empty()) return nullptr;
  auto *buf = static_cast<uint8_t *>(malloc(node->observed_host.size()));
  memcpy(buf, node->observed_host.data(), node->observed_host.size());
  *out_len = node->observed_host.size();
  return buf;
}

int swarm_node_has_direct(SwarmNode *node, const uint8_t target[32]) {
  NodeId tid;
  memcpy(tid.data(), target, 32);
  std::lock_guard<std::mutex> g(node->dl_mu);
  return node->direct_links.count(tid) ? 1 : 0;
}

uint64_t swarm_node_relay_served(SwarmNode *node) {
  return node->relay_served.load();
}

uint8_t *swarm_node_peers(SwarmNode *node, size_t *out_len) {
  auto peers = node->rt.dump();
  std::string out;
  put_u32(out, uint32_t(peers.size()));
  for (const auto &p : peers) {
    out.append(reinterpret_cast<const char *>(p.id.data()), 32);
    put_bytes(out, reinterpret_cast<const uint8_t *>(p.host.data()),
              p.host.size());
    put_u16(out, p.port);
  }
  auto *buf = static_cast<uint8_t *>(malloc(out.size()));
  memcpy(buf, out.data(), out.size());
  *out_len = out.size();
  return buf;
}

void swarm_node_destroy(SwarmNode *node) {
  node->running.store(false);
  if (node->listen_fd >= 0) {
    shutdown(node->listen_fd, SHUT_RDWR);
    close(node->listen_fd);
  }
  if (node->acceptor.joinable()) node->acceptor.join();
  /* unblock idle per-connection handler reads (pooled peers, attachments) */
  {
    std::lock_guard<std::mutex> g(node->hfd_mu);
    for (int fd : node->handler_fds) shutdown(fd, SHUT_RDWR);
  }
  {
    std::lock_guard<std::mutex> g(node->my_relay_mu);
    if (node->my_relay_fd >= 0) {
      shutdown(node->my_relay_fd, SHUT_RDWR);
      close(node->my_relay_fd);
      node->my_relay_fd = -1;
    }
    if (node->my_relay_reader.joinable()) node->my_relay_reader.join();
  }
  /* tear down punched links + prepared punch sockets (their reader
   * threads follow the handler lifecycle: the handler_fds shutdown
   * above unblocked them, live_handlers below waits them out) */
  {
    std::lock_guard<std::mutex> g(node->dl_mu);
    for (auto &kv : node->direct_links) shutdown(kv.second.fd, SHUT_RDWR);
    for (auto &kv : node->punch_sockets) {
      shutdown(kv.second, SHUT_RDWR);
      close(kv.second);
    }
    node->punch_sockets.clear();
  }
  node->pool_clear();
  /* Wait for in-flight handler threads: they hold `node`, so deleting
   * early is a use-after-free. The wait is bounded by the socket
   * timeouts the handlers run under (SO_RCVTIMEO/SO_SNDTIMEO). */
  while (node->live_handlers.load() > 0)
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  delete node;
}

void swarm_free(uint8_t *buf) { free(buf); }

}  /* extern "C" */
